"""Sampled distributed request tracing (ISSUE 18).

A trace is one user-visible unit of work — one ``ServeClient.project``
call, or one launcher-driven batch run — stitched across processes by a
16-hex trace id. Each hop emits ``span`` events (schema-registered in
``utils/telemetry.EVENT_TYPES``) into whatever run telemetry JSONL it
already writes; the O_APPEND single-write discipline means client,
daemon, parent, and worker spans interleave safely in one file.

Propagation:

* serve path — ``ServeClient`` samples per request
  (``CNMF_TPU_TRACE_SAMPLE``), sends ``X-CNMF-Trace: <trace>:<span>``;
  the daemon parses it and threads a child context through admission,
  batcher queueing, linger, and the AOT dispatch.
* batch path — the launcher samples once per run and serializes the
  root context into ``CNMF_TPU_TRACE_CTX`` in each worker's env;
  workers (and the store backend under them) pick it up via
  :func:`process_context`.

Sampling is DETERMINISTIC in the trace id: the keep/drop decision is a
pure function of (trace_id, rate), so every process that sees a context
agrees it is sampled — there is no per-hop coin flip to lose spans
mid-trace. Unsampled work creates no context at all (``new_trace``
returns ``None``) and every emit helper is a no-op on ``None``, which
is what keeps the off path at literally zero work.

``cnmf-tpu trace <run_dir>`` renders the collected spans as
per-trace waterfalls (queue wait vs batch linger vs device dispatch vs
store I/O) via :func:`render_run_traces`.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from contextlib import contextmanager

from ..utils.envknobs import env_float, env_str

__all__ = [
    "TRACE_SAMPLE_ENV", "TRACE_CTX_ENV", "TRACE_HEADER", "TraceContext",
    "sample_rate", "is_sampled", "new_trace", "child", "header_value",
    "from_header", "env_value", "from_env", "process_context",
    "reset_process_context", "emit_span", "span", "perf_to_wall",
    "load_traces", "render_waterfall", "render_run_traces",
]

TRACE_SAMPLE_ENV = "CNMF_TPU_TRACE_SAMPLE"
TRACE_CTX_ENV = "CNMF_TPU_TRACE_CTX"
TRACE_HEADER = "X-CNMF-Trace"


class TraceContext:
    """Immutable (trace, span, parent) triple. ``span_id`` names the
    span the HOLDER is inside; emitting with this context writes
    ``span=span_id, parent=parent_id``. Children get fresh span ids
    parented on this one."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: str, parent_id=None):
        self.trace_id = str(trace_id)
        self.span_id = str(span_id)
        self.parent_id = None if parent_id is None else str(parent_id)

    def __repr__(self):
        return ("TraceContext(trace=%s, span=%s, parent=%s)"
                % (self.trace_id, self.span_id, self.parent_id))


_ID_LOCK = threading.Lock()
_ID_COUNTER = [0]  # per-process span sequence; bumped under _ID_LOCK


def _new_span_id() -> str:
    with _ID_LOCK:
        _ID_COUNTER[0] += 1
        n = _ID_COUNTER[0]
    return "%x.%x" % (os.getpid(), n)


def sample_rate() -> float:
    """The ``CNMF_TPU_TRACE_SAMPLE`` probability in [0, 1]; 0 (the
    default) disables tracing entirely."""
    return env_float(TRACE_SAMPLE_ENV, 0.0, lo=0.0, hi=1.0)


def is_sampled(trace_id: str, rate=None) -> bool:
    """Deterministic keep/drop: hash the trace id into [0, 1) and keep
    when it falls under the rate. Same id + same rate -> same answer in
    every process, pinned by test."""
    r = sample_rate() if rate is None else float(rate)
    if r <= 0.0:
        return False
    if r >= 1.0:
        return True
    import hashlib

    h = hashlib.sha256(trace_id.encode("ascii")).digest()
    return int.from_bytes(h[:8], "big") / float(1 << 64) < r


def new_trace(rate=None):
    """Start a new root trace, or ``None`` when sampling says drop (or
    tracing is off). Root span id doubles as the trace's top of tree."""
    r = sample_rate() if rate is None else float(rate)
    if r <= 0.0:
        return None
    trace_id = uuid.uuid4().hex[:16]
    if not is_sampled(trace_id, r):
        return None
    return TraceContext(trace_id, _new_span_id())


def child(ctx):
    """A fresh span context parented on ``ctx`` (None-propagating)."""
    if ctx is None:
        return None
    return TraceContext(ctx.trace_id, _new_span_id(), ctx.span_id)


# -- wire formats -----------------------------------------------------------

def header_value(ctx) -> str:
    return "%s:%s" % (ctx.trace_id, ctx.span_id)


def from_header(value):
    """Parse an ``X-CNMF-Trace`` header; malformed values are dropped
    (tracing must never fail a request)."""
    if not value:
        return None
    parts = str(value).split(":")
    if len(parts) != 2 or not all(parts):
        return None
    return TraceContext(parts[0], parts[1])


env_value = header_value  # same trace:span serialization on both wires


def from_env():
    """The context serialized into ``CNMF_TPU_TRACE_CTX`` by a launcher
    parent, or ``None``."""
    return from_header(env_str(TRACE_CTX_ENV, ""))


_PROC_LOCK = threading.Lock()
_PROC_CTX: list = []  # memoized [ctx-or-None]; set once under _PROC_LOCK


def process_context():
    """This process's ambient trace context (from env), memoized — the
    batch-path analogue of the serve path's per-request header."""
    with _PROC_LOCK:
        if not _PROC_CTX:
            _PROC_CTX.append(from_env())
        return _PROC_CTX[0]


def reset_process_context() -> None:
    """Tests only: re-read ``CNMF_TPU_TRACE_CTX`` on next use."""
    with _PROC_LOCK:
        _PROC_CTX.clear()


# -- span emission ----------------------------------------------------------

def perf_to_wall(t_perf: float) -> float:
    """Convert a ``time.perf_counter`` stamp into the wall-clock epoch
    used by span ``start_ts``, so spans timed with perf_counter deltas
    (the batcher's request stamps) land on the same axis as everyone
    else's."""
    return time.time() - (time.perf_counter() - t_perf)


def emit_span(events, ctx, name: str, start_ts: float, wall_ms: float,
              **context) -> None:
    """Append one schema-valid ``span`` event; no-op without an enabled
    event log or a sampled context. Never raises past the event layer
    (``EventLog.emit`` already swallows I/O errors)."""
    if ctx is None or events is None:
        return
    if not getattr(events, "enabled", False):
        return
    events.emit("span", trace=ctx.trace_id, span=ctx.span_id,
                parent=ctx.parent_id, name=str(name),
                start_ts=float(start_ts),
                wall_ms=round(float(wall_ms), 3),
                context=context or None)


@contextmanager
def span(events, ctx, name: str, **context):
    """Time a block as one span. ``ctx`` should already be the CHILD
    context for this span (see :func:`child`); yields it so nested
    spans can parent on it."""
    if ctx is None or events is None or not getattr(events, "enabled",
                                                    False):
        yield None
        return
    t_wall = time.time()
    t0 = time.perf_counter()
    try:
        yield ctx
    finally:
        emit_span(events, ctx, name, start_ts=t_wall,
                  wall_ms=(time.perf_counter() - t0) * 1e3, **context)


# -- waterfall rendering (cnmf-tpu trace) -----------------------------------

def load_traces(run_dir: str) -> dict:
    """Collect every ``span`` event under ``<run_dir>/cnmf_tmp/`` into
    ``{trace_id: [span dict, ...]}`` (each sorted by start_ts)."""
    from ..utils.telemetry import _find_event_files, read_events

    traces: dict = {}
    for path in _find_event_files(run_dir):
        try:
            events = read_events(path)
        except (OSError, ValueError):
            continue
        for ev in events:
            if ev.get("t") != "span":
                continue
            traces.setdefault(ev.get("trace", "?"), []).append(ev)
    for spans in traces.values():
        spans.sort(key=lambda e: (e.get("start_ts", 0.0),
                                  e.get("span", "")))
    return traces


def _span_depth(ev: dict, by_id: dict) -> int:
    depth, seen = 0, set()
    parent = ev.get("parent")
    while parent and parent in by_id and parent not in seen:
        seen.add(parent)
        depth += 1
        parent = by_id[parent].get("parent")
    return depth


def render_waterfall(trace_id: str, spans: list, width: int = 40) -> str:
    """One trace as an indented waterfall: bar position = span start
    offset within the trace, bar length = wall time, both to scale."""
    if not spans:
        return "trace %s: no spans" % trace_id
    by_id = {ev.get("span"): ev for ev in spans}
    t_lo = min(ev.get("start_ts", 0.0) for ev in spans)
    t_hi = max(ev.get("start_ts", 0.0) + ev.get("wall_ms", 0.0) / 1e3
               for ev in spans)
    total_ms = max((t_hi - t_lo) * 1e3, 1e-6)
    name_w = max(len("  " * _span_depth(ev, by_id) + str(ev.get("name")))
                 for ev in spans)
    lines = ["trace %s — %d span(s), %.1f ms total"
             % (trace_id, len(spans), total_ms)]
    for ev in spans:
        off_ms = (ev.get("start_ts", 0.0) - t_lo) * 1e3
        wall_ms = float(ev.get("wall_ms", 0.0))
        lo = int(round(off_ms / total_ms * width))
        ln = max(1, int(round(wall_ms / total_ms * width)))
        lo = min(lo, width - 1)
        ln = min(ln, width - lo)
        bar = " " * lo + "#" * ln + " " * (width - lo - ln)
        label = "  " * _span_depth(ev, by_id) + str(ev.get("name"))
        ctx = ev.get("context") or {}
        suffix = ("  [%s]" % ",".join("%s=%s" % kv
                                      for kv in sorted(ctx.items()))
                  if ctx else "")
        lines.append("  %-*s |%s| %8.2f ms @ +%.2f ms%s"
                     % (name_w, label, bar, wall_ms, off_ms, suffix))
    return "\n".join(lines)


def render_run_traces(run_dir: str, limit: int = 10) -> str:
    """Every sampled trace in a run directory, newest first, capped at
    ``limit`` waterfalls (the cap is stated, never silent)."""
    traces = load_traces(run_dir)
    if not traces:
        return ("no span events under %s — run with "
                "CNMF_TPU_TELEMETRY=1 and CNMF_TPU_TRACE_SAMPLE>0"
                % run_dir)
    order = sorted(traces,
                   key=lambda tid: traces[tid][0].get("start_ts", 0.0),
                   reverse=True)
    shown = order[:limit]
    parts = ["%d trace(s) in %s" % (len(traces), run_dir)]
    if len(order) > len(shown):
        parts[0] += " (showing newest %d)" % len(shown)
    for tid in shown:
        parts.append("")
        parts.append(render_waterfall(tid, traces[tid]))
    return "\n".join(parts)
