"""Measured solver-cost ratios: a startup microbench cached per device
fingerprint (ISSUE 11 satellite, feeding ROADMAP item 5's autotuner).

The accelerated-MU schedule (``ops/recipe.py:auto_inner_repeats``) derives
ρ — H sub-iterations per W update — from STATIC flop-count ratios whose
clamp was measured once on CPU. Real kernels diverge from flop counts
(gather-bound ELL passes, fusion, memory formats differ per backend), so
this module times one H-repeat against one W-update per lane on the LIVE
device at a probe shape, stores ``measured_ratio / static_ratio`` per
lane, and ``auto_inner_repeats`` multiplies its static ratio by that
scale (falling back to the static schedule whenever no cache exists).

The cache is one JSON per device fingerprint under the system temp dir
(atomic replace; survives processes, not reboots on tmpfs — the bench is
~1 s, so a cold cache is cheap). ``models/cnmf.py:factorize`` calls
:func:`maybe_autotune_rho` once up front when the accel knobs could
engage an amu recipe; everything here is best-effort — any failure
resolves to the static schedule, never an error.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time

__all__ = ["device_fingerprint", "cache_path", "measure_rho_scales",
           "maybe_autotune_rho", "cached_rho_scale",
           "measure_plan_points", "maybe_autotune_plan",
           "cached_plan_point", "cached_plan_points", "AUTOTUNE_ENV"]

AUTOTUNE_ENV = "CNMF_TPU_AUTOTUNE"

_OFF_WORDS = ("", "0", "off", "false", "no")
_ON_WORDS = ("1", "on", "true", "yes", "force")

_PROBE_N, _PROBE_G, _PROBE_K = 2048, 512, 10
_PROBE_DENSITY = 0.05

_memo: dict = {}
_memo_lock = threading.Lock()


def device_fingerprint() -> str:
    """Package version + backend + device kind + count — the identity a
    measured point is valid for. The PACKAGE VERSION is part of the
    fingerprint (ISSUE 17 satellite): a version bump changes the cache
    path outright, so stale crossovers measured against older kernels
    are orphaned instead of silently reused (a resumed run on different
    hardware re-measures for the same reason)."""
    import jax

    try:
        from ..version import __version__ as pkg_version
    except Exception:
        pkg_version = "unknown"
    d = jax.devices()[0]
    kind = str(getattr(d, "device_kind", "unknown")).replace(" ", "_")
    return (f"v{pkg_version}-{jax.default_backend()}-{kind}"
            f"-x{len(jax.devices())}")


def autotune_mode() -> str:
    """The ``CNMF_TPU_AUTOTUNE`` word, normalized to ``off`` | ``auto``
    | ``force``. ``off`` disables measuring AND consuming (static
    heuristics only — the deterministic escape hatch); ``auto`` (the
    default) consumes an existing cache but only measures when an
    explicitly engaged lane needs it; ``force`` measures all plan
    points up front."""
    from .envknobs import env_str

    raw = env_str(AUTOTUNE_ENV, "auto").strip().lower()
    if raw in _OFF_WORDS:
        return "off"
    if raw in _ON_WORDS:
        return "force"
    if raw == "auto":
        return "auto"
    raise ValueError(f"{AUTOTUNE_ENV}={raw!r}: expected 0, 1, or auto")


def cache_path(cache_dir: str | None = None) -> str:
    base = cache_dir or os.path.join(tempfile.gettempdir(),
                                     "cnmf_tpu_autotune")
    return os.path.join(base, f"rho_{device_fingerprint()}.json")


def _time_call(fn, *args, repeats: int = 5) -> float:
    """Median wall of ``fn(*args)`` with block_until_ready, after one
    warm-up dispatch (compile + upload excluded from the measurement)."""
    import jax

    jax.block_until_ready(fn(*args))
    walls = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        walls.append(time.perf_counter() - t0)
    walls.sort()
    return walls[len(walls) // 2]


def measure_rho_scales() -> dict:
    """Run the microbench: per lane, the measured W-update/H-repeat wall
    ratio divided by the static flop ratio ``auto_inner_repeats`` would
    use at the probe shape. Returns the cache payload."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import scipy.sparse as sp

    from ..ops.nmf import _apply_rate, _update_H, _update_W
    from ..ops.sparse import csr_to_ell, ell_device_put, ell_w_table

    n, g, k = _PROBE_N, _PROBE_G, _PROBE_K
    rng = np.random.default_rng(0)
    H = jnp.asarray(rng.uniform(0.1, 1.0, (n, k)).astype(np.float32))
    W = jnp.asarray(rng.uniform(0.1, 1.0, (k, g)).astype(np.float32))
    Xd = jnp.asarray(rng.gamma(1.0, 1.0, (n, g)).astype(np.float32))

    scales: dict = {}

    # beta=2: H repeat = rate against hoisted XW^T/WW^T (k-sized);
    # W update = the full statistics step
    numer0 = Xd @ W.T
    WWT = W @ W.T
    h_rep_b2 = jax.jit(lambda h: _apply_rate(h, numer0, h @ WWT, 0.0, 0.0))
    w_upd_b2 = jax.jit(lambda h, w: _update_W(Xd, h, w, 2.0, 0.0, 0.0))
    static_b2 = (2.0 * n * g * k) / max(n * k * k, 1)
    meas_b2 = (_time_call(w_upd_b2, H, W)
               / max(_time_call(h_rep_b2, H), 1e-9))
    scales["b2"] = meas_b2 / static_b2

    # dense beta=1: repeat and W update are the same full-pass class
    h_rep_kl = jax.jit(lambda h: _update_H(Xd, h, W, 1.0, 0.0, 0.0))
    w_upd_kl = jax.jit(lambda h, w: _update_W(Xd, h, w, 1.0, 0.0, 0.0))
    scales["dense"] = (_time_call(w_upd_kl, H, W)
                       / max(_time_call(h_rep_kl, H), 1e-9)) / 1.0

    # ELL beta=1: repeat reads the pre-gathered slab table; the W update
    # rebuilds tables and walks the transpose index set
    mask = rng.uniform(size=(n, g)) < _PROBE_DENSITY
    Xs = sp.csr_matrix(np.where(mask, np.asarray(Xd), 0.0))
    E = ell_device_put(csr_to_ell(Xs))
    w_ell = E.width
    table = ell_w_table(W, E.cols)
    h_rep_ell = jax.jit(
        lambda h: _update_H(E, h, W, 1.0, 0.0, 0.0, w_table=table))
    w_upd_ell = jax.jit(lambda h, w: _update_W(E, h, w, 1.0, 0.0, 0.0))
    static_ell = (n * w_ell * (4 * k + 2)) / max(n * w_ell * (2 * k + 2), 1)
    scales["ell"] = (_time_call(w_upd_ell, H, W)
                     / max(_time_call(h_rep_ell, H), 1e-9)) / static_ell

    return {"fingerprint": device_fingerprint(),
            "probe": {"n": n, "g": g, "k": k,
                      "density": _PROBE_DENSITY, "ell_width": int(w_ell)},
            "scales": {lane: round(float(v), 4)
                       for lane, v in scales.items()},
            "measured_at": time.time()}


def _load(path: str) -> dict | None:
    try:
        with open(path) as f:
            payload = json.load(f)
        if payload.get("fingerprint") != device_fingerprint():
            return None
        return payload
    except Exception:
        return None


def maybe_autotune_rho(cache_dir: str | None = None,
                       force: bool = False,
                       beta: float | None = None) -> dict | None:
    """Ensure the measured-ρ cache for this device exists and is loaded
    into the in-process memo. Measures (and atomically writes the JSON)
    only when no valid cache is present, and only when the accel knobs
    could actually engage an amu schedule — ``CNMF_TPU_ACCEL`` off or an
    explicit ``CNMF_TPU_INNER_REPEATS`` pin means the measurement would
    never be read, so the bench is skipped. Best-effort: returns the
    payload or ``None``; never raises.

    Determinism: the measured ρ is a jit static and part of the
    checkpoint identity signature, so it must agree wherever programs
    must agree. On MULTI-HOST pods the lane is disabled outright
    (``jax.process_count() > 1`` → static schedule): per-host timing
    jitter could resolve different ρ on different hosts and compile
    mismatched SPMD programs. Single-host, a lost cache re-measures and
    may land a different ρ — the checkpoint identity then RESTARTS the
    replicate (the documented recipe-change contract, never a splice);
    pin ``CNMF_TPU_INNER_REPEATS`` for resume-stable long runs."""
    try:
        from .envknobs import env_str

        mode = autotune_mode()
        if mode == "off" and not force:
            return None
        if not force and mode != "force":
            # lazy mode: measure only when the accel knobs EXPLICITLY
            # engage an amu schedule. The "auto" accel default (ISSUE 17)
            # deliberately does not trigger measurement — a stock run
            # stays deterministic on a cold machine and uses the static
            # ρ schedule; an existing cache is still consumed
            # (precedence pin > autotuned > heuristic), and
            # CNMF_TPU_AUTOTUNE=1 measures up front.
            accel = env_str("CNMF_TPU_ACCEL", "auto").strip().lower()
            rho_pin = env_str("CNMF_TPU_INNER_REPEATS", "").strip().lower()
            if accel not in _ON_WORDS or rho_pin not in ("", "auto"):
                return None
            # amu-reachability (``beta`` known): a run whose engaged
            # recipe can only be sketch (CNMF_TPU_SKETCH forces the
            # solver lane for beta=1) or dna (KL_NEWTON on steers an
            # engaged beta=1 acceleration to Newton) never consults
            # auto_inner_repeats — skip the bench instead of paying a
            # ~1 s startup it cannot read
            if beta is not None and float(beta) == 1.0:
                from .envknobs import env_flag

                sk = env_str("CNMF_TPU_SKETCH", "0").strip().lower()
                if sk in ("1", "on", "true", "yes", "force") or \
                        env_flag("CNMF_TPU_KL_NEWTON", True):
                    return None
        import jax

        if jax.process_count() > 1:
            return None
        path = cache_path(cache_dir)
        payload = None if force else _load(path)
        if payload is None or "scales" not in payload:
            payload = _merge_write(path, measure_rho_scales())
        with _memo_lock:
            _memo[path] = payload
        return payload
    except Exception:
        return None


def _merge_write(path: str, updates: dict) -> dict:
    """Merge ``updates`` into the device's cache payload and atomically
    rewrite it (the ρ scales and the plan points share one file, so a
    later measurement must not clobber an earlier section)."""
    payload = _load(path) or {}
    payload.update(updates)
    payload["fingerprint"] = device_fingerprint()
    payload["measured_at"] = time.time()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    from .anndata_lite import atomic_artifact

    with atomic_artifact(path) as tmp:
        with open(tmp, "w") as f:
            json.dump(payload, f)
    with _memo_lock:
        _memo[path] = payload
    return payload


def measure_plan_points() -> dict:
    """Run the PLANNER microbenches (ISSUE 17): one measured value per
    dispatch decision the static heuristics in
    ``runtime/planner.py:build_plan`` would otherwise guess. Every point
    is individually best-effort — a lane that fails to measure is simply
    absent from the dict and the planner keeps its static default for
    that decision. Points:

      * ``ell_density_crossover`` — the density below which the ELL
        encoding beats the dense chain, extrapolated from the probe-
        density wall ratio (ELL pass cost scales ~linearly with width,
        dense is density-blind), clamped to [0.01, 0.5].
      * ``pallas_wins`` — fused-Pallas vs jnp ELL H-statistics wall
        (TPU backends only: interpret mode is not a perf signal).
      * ``grid_blocks`` — fastest per-axis chunk count for the chunked
        statistics pass among {1, 2, 4, 8}.
      * ``stream_threads`` — fastest host→device slab-staging thread
        count among {1, 2, 4} (depth follows as ``2*threads + 1``).
      * ``sketch_dim`` — largest probe-scaled sketch row count whose
        W-update wall is at most half the exact update (the sketch
        recipe's break-even contract); recorded as rows per 2048 cells
        so the planner can rescale to the live ``n``.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import scipy.sparse as sp

    from ..ops.nmf import _update_H, _update_W
    from ..ops.sparse import csr_to_ell, ell_device_put, ell_w_table

    n, g, k = _PROBE_N, _PROBE_G, _PROBE_K
    rng = np.random.default_rng(0)
    H = jnp.asarray(rng.uniform(0.1, 1.0, (n, k)).astype(np.float32))
    W = jnp.asarray(rng.uniform(0.1, 1.0, (k, g)).astype(np.float32))
    Xd = jnp.asarray(rng.gamma(1.0, 1.0, (n, g)).astype(np.float32))
    mask = rng.uniform(size=(n, g)) < _PROBE_DENSITY
    Xs = sp.csr_matrix(np.where(mask, np.asarray(Xd), 0.0))
    E = ell_device_put(csr_to_ell(Xs))
    table = ell_w_table(W, E.cols)

    points: dict = {}

    # ELL-vs-dense crossover: at the probe density the walls are
    # dense_w (flat in density) and ell_w (~linear in width ∝ density),
    # so equal-cost density ≈ probe_density * dense_w / ell_w
    try:
        h_dense = jax.jit(lambda h: _update_H(Xd, h, W, 1.0, 0.0, 0.0))
        h_ell = jax.jit(
            lambda h: _update_H(E, h, W, 1.0, 0.0, 0.0, w_table=table))
        dense_w = _time_call(h_dense, H)
        ell_w = max(_time_call(h_ell, H), 1e-9)
        points["ell_density_crossover"] = round(
            min(0.5, max(0.01, _PROBE_DENSITY * dense_w / ell_w)), 4)
    except Exception:
        pass

    # Pallas-vs-jnp: only a real TPU lowering is a perf signal
    # (interpret mode times the reference interpreter, not the kernel)
    try:
        from ..ops.pallas import pallas_available, pallas_interpret

        if pallas_available() and not pallas_interpret():
            h_jnp = jax.jit(
                lambda h: _update_H(E, h, W, 1.0, 0.0, 0.0, w_table=table))
            h_pl = jax.jit(lambda h: _update_H(
                E, h, W, 1.0, 0.0, 0.0, w_table=table, use_pallas=True))
            points["pallas_wins"] = bool(
                _time_call(h_pl, H) < _time_call(h_jnp, H))
    except Exception:
        pass

    # grid block count: wall of the row-chunked dense statistics pass
    # (the grid2d overlap unit) at each candidate chunking
    try:
        walls = {}
        for nb in (1, 2, 4, 8):
            rows = n // nb
            h_blk = jax.jit(
                lambda h, x: _update_H(x, h, W, 1.0, 0.0, 0.0))

            def run_blocks(nb=nb, rows=rows, h_blk=h_blk):
                return [h_blk(H[i * rows:(i + 1) * rows],
                              Xd[i * rows:(i + 1) * rows])
                        for i in range(nb)]

            walls[nb] = _time_call(run_blocks)
        points["grid_blocks"] = int(min(walls, key=walls.get))
    except Exception:
        pass

    # slab-staging threads: host->device put throughput over 16 slabs
    try:
        from concurrent.futures import ThreadPoolExecutor

        slabs = [np.asarray(rng.gamma(1.0, 1.0, (128, g)),
                            dtype=np.float32) for _ in range(16)]
        dev = jax.devices()[0]

        def stage_all(n_threads):
            with ThreadPoolExecutor(max_workers=n_threads) as pool:
                futs = [pool.submit(jax.device_put, s, dev) for s in slabs]
                jax.block_until_ready([f.result() for f in futs])

        t_walls = {}
        for nt in (1, 2, 4):
            stage_all(nt)  # warm-up
            w0 = []
            for _ in range(3):
                t0 = time.perf_counter()
                stage_all(nt)
                w0.append(time.perf_counter() - t0)
            t_walls[nt] = sorted(w0)[1]
        points["stream_threads"] = int(min(t_walls, key=t_walls.get))
    except Exception:
        pass

    # sketch dim: largest row-subsample whose W update costs at most
    # half the exact one (recorded per 2048 probe cells)
    try:
        w_exact = jax.jit(lambda h, w: _update_W(Xd, h, w, 1.0, 0.0, 0.0))
        exact_wall = _time_call(w_exact, H, W)
        best = None
        for m in (n // 16, n // 8, n // 4):
            Xm, Hm = Xd[:m], H[:m]
            w_sk = jax.jit(
                lambda h, w, x=Xm: _update_W(x, h, w, 1.0, 0.0, 0.0))
            if _time_call(w_sk, Hm, W) <= 0.5 * exact_wall:
                best = int(m)
        if best is not None:
            points["sketch_dim"] = best
    except Exception:
        pass

    return points


def maybe_autotune_plan(cache_dir: str | None = None,
                        force: bool = False) -> dict | None:
    """Ensure the plan-point section of the device cache exists.
    MEASURES only under ``CNMF_TPU_AUTOTUNE=1`` (force mode) or an
    explicit ``force=True`` — the ``auto`` default consumes an existing
    cache without ever paying the bench on a stock run, keeping cold-
    machine dispatch deterministic (the static heuristics). Multi-host
    pods never measure nor consume (plan points feed jit statics that
    must agree across SPMD hosts). Returns the full cache payload or
    ``None``; best-effort, never raises."""
    try:
        mode = autotune_mode()
        if mode == "off" and not force:
            return None
        import jax

        if jax.process_count() > 1:
            return None
        path = cache_path(cache_dir)
        payload = _load(path)
        if force or mode == "force":
            if force or payload is None or "plan_points" not in payload:
                payload = _merge_write(
                    path, {"plan_points": measure_plan_points()})
        if payload is not None:
            with _memo_lock:
                _memo[path] = payload
        return payload
    except Exception:
        return None


def cached_plan_points(cache_dir: str | None = None) -> dict:
    """Read-only: the measured plan points for this device fingerprint,
    or ``{}``. Never measures. Same consumption gates as
    :func:`cached_rho_scale`: ``CNMF_TPU_AUTOTUNE=0`` and multi-host
    pods always get ``{}``."""
    try:
        if autotune_mode() == "off":
            return {}
        import jax

        if jax.process_count() > 1:
            return {}
        path = cache_path(cache_dir)
        with _memo_lock:
            payload = _memo.get(path)
        if payload is None:
            payload = _load(path)
            if payload is None:
                return {}
            with _memo_lock:
                _memo[path] = payload
        pts = payload.get("plan_points")
        return dict(pts) if isinstance(pts, dict) else {}
    except Exception:
        return {}


def cached_plan_point(name: str, cache_dir: str | None = None):
    """One measured plan point by name, or ``None`` when absent (the
    caller keeps its static heuristic). The consumption sites:
    ``runtime/planner.py`` (ell_density_crossover, grid/stream points),
    ``ops/pallas`` (pallas_wins), ``ops/recipe.py`` (sketch_dim)."""
    return cached_plan_points(cache_dir).get(name)


def cached_rho_scale(beta: float, ell: bool = False,
                     cache_dir: str | None = None) -> float | None:
    """Read-only lane lookup for ``auto_inner_repeats``: the measured
    scale for this (β, encoding) lane, or ``None`` (static fallback)
    when no cache has been written for this device. Never measures.
    Multi-host pods always get ``None`` — a cache written by an earlier
    single-host run on one machine must not steer ρ differently across
    hosts compiling one SPMD program (see :func:`maybe_autotune_rho`).
    ``CNMF_TPU_AUTOTUNE=0`` also gets ``None`` — the deterministic
    static-heuristics escape hatch disables consumption, not just
    measurement."""
    try:
        if autotune_mode() == "off":
            return None
        import jax

        if jax.process_count() > 1:
            return None
        path = cache_path(cache_dir)
        with _memo_lock:
            payload = _memo.get(path)
        if payload is None:
            payload = _load(path)
            if payload is None:
                return None
            with _memo_lock:
                _memo[path] = payload
        lane = "b2" if float(beta) == 2.0 else ("ell" if ell else "dense")
        val = payload.get("scales", {}).get(lane)
        return float(val) if val is not None else None
    except Exception:
        return None
