"""True 2-D (cells x genes) processor grid with compute-overlapped
collectives — the MPI-FAUN layout (arXiv 1609.09154).

The package's earlier "2-D mesh" (:mod:`.multihost`) is replicates x
cells: every device still holds full gene rows of W, so the mesh scales
the sweep and the cells axis but not the GENE axis — a wide atlas (many
genes, or k x g too big for one chip's replication) has nowhere to go.
This module shards BOTH data axes:

  * ``X`` lives as (cells, genes) blocks — each device holds an
    (n/c_dim, g/g_dim) tile, staged by :func:`stage_x_grid` straight
    from a host matrix or a :class:`~cnmf_torch_tpu.utils.shardstore.
    ShardStore` (row-stripe reads, no full-matrix host copy).
  * ``H`` (cells x k) shards over the cells axis, replicated along
    genes; ``W`` (k x genes) shards over the genes axis, replicated
    along cells — MPI-FAUN's factor distribution.
  * Every update statistic is an AXIS-LOCAL reduction: the H-side
    numerators (``X Wᵀ``-shaped, O(rows x k)) psum over the GENES axis
    only, the W-side sufficient statistics (``Hᵀ X`` (k x g_loc),
    ``Hᵀ H`` (k x k)) psum over the CELLS axis only. No collective ever
    spans the full grid except the scalar objective.

DCN-aware axis assignment (:func:`mesh_grid2d`): on a multi-host pod
the CELLS axis is laid across hosts and the GENES axis stays within a
host — the large per-pass H-side reductions (O(rows x k), and per inner
iteration for KL/IS) ride ICI, while only the small k x g_loc / k x k
W-side statistics cross DCN. Single-host grids factor most-square with
cells taking the larger factor.

Compute-overlapped collectives (the MPI-FAUN overlap): the statistics
contractions are split into ``CNMF_TPU_GRID_BLOCKS`` sub-blocks and the
psum for block *i* is dispatched while block *i+1*'s local gemm
computes (:func:`_overlapped_psum` — a double-buffered, Python-unrolled
loop the XLA latency-hiding scheduler can interleave).
``CNMF_TPU_GRID_OVERLAP=0`` chains an ``optimization_barrier`` between
each reduce and the next gemm instead — SAME partial-sum order, so the
two modes are bit-identical in results and differ only in scheduling
freedom; :func:`measure_collectives` times the two against a
collectives-only probe to report the hidden-collective fraction
(``bench.py --tier grid2d``, telemetry ``collective`` events).

Solver semantics match :func:`~cnmf_torch_tpu.parallel.rowshard.
nmf_fit_rowsharded` (block-coordinate passes, tightly solved usage
blocks, statistics-based W subproblem, same f32 convergence
arithmetic); the plain-MU lanes for beta in {2, 1, 0} and the
Diagonalized-Newton KL recipe (``kl_newton``) are implemented on the
grid. Parity with the 1-D path is to collective-reduction rounding
(the gene axis splits contractions the 1-D path runs whole).
"""

from __future__ import annotations

import functools
import math
import time

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.jax_compat import shard_map
from ..utils.shardstore import ShardStore, SlabCursor

from ..ops.nmf import (
    EPS,
    TRACE_LEN,
    _apply_rate,
    _beta_div_dense,
    beta_loss_to_float,
    mu_gamma,
    random_init,
    resolve_online_schedule,
    split_regularization,
)

__all__ = [
    "mesh_grid2d",
    "stage_x_grid",
    "nmf_fit_grid2d",
    "measure_collectives",
    "grid_overlap_enabled",
    "grid_blocks",
]

GRID_OVERLAP_ENV = "CNMF_TPU_GRID_OVERLAP"
GRID_BLOCKS_ENV = "CNMF_TPU_GRID_BLOCKS"
GRID_SHAPE_ENV = "CNMF_TPU_GRID_SHAPE"


def grid_overlap_enabled() -> bool:
    """``CNMF_TPU_GRID_OVERLAP``: dispatch each statistics block's
    collective while the next block's gemm computes (default on).
    ``0`` serializes reduce -> gemm with an optimization barrier —
    bit-identical results, no overlap (the bench baseline)."""
    from ..utils.envknobs import env_flag

    return env_flag(GRID_OVERLAP_ENV, True)


def grid_blocks(extent: int) -> int:
    """Statistics sub-blocks for the overlap loop, clamped to a divisor
    of ``extent`` (the local rows/cols being blocked). ``0`` (default)
    derives: 4 blocks when the extent affords them, fewer otherwise."""
    from ..utils.envknobs import env_int

    want = env_int(GRID_BLOCKS_ENV, 0, lo=0)
    if want <= 0:
        # planner precedence (ISSUE 17): no explicit pin -> the measured
        # chunk-count point from the autotune cache when one exists for
        # this device, else the static 4-when-affordable heuristic
        try:
            from ..utils.autotune import cached_plan_point

            tuned = cached_plan_point("grid_blocks")
            want = int(tuned) if tuned else 0
        except Exception:
            want = 0
    if want <= 0:
        want = 4 if extent >= 64 else 1
    want = max(1, min(int(want), max(int(extent), 1)))
    while want > 1 and extent % want:
        want -= 1
    return want


def _grid_rc(n_dev: int, n_proc: int) -> tuple[int, int]:
    """Factor the device count into (cell_shards, gene_shards).

    ``CNMF_TPU_GRID_SHAPE=CxG`` pins it. Multi-host: the CELLS axis
    spans hosts (gene_shards = devices per host), so the O(rows x k)
    H-side statistics reduce stays on ICI and only the k x g_loc /
    k x k W-side reductions cross DCN. Single host: most-square, cells
    taking the larger factor (cell counts exceed gene counts in every
    BASELINE config)."""
    from ..utils.envknobs import env_str

    raw = env_str(GRID_SHAPE_ENV, "auto").strip().lower()
    if raw and raw != "auto":
        try:
            c_s, g_s = raw.split("x")
            c, g = int(c_s), int(g_s)
        except ValueError:
            raise ValueError(
                f"{GRID_SHAPE_ENV}={raw!r}: expected 'CxG' (e.g. '4x2') "
                "or 'auto'") from None
        if c < 1 or g < 1 or c * g != n_dev:
            raise ValueError(
                f"{GRID_SHAPE_ENV}={raw!r}: {c}x{g} != {n_dev} devices")
        return c, g
    if n_proc > 1 and n_dev % n_proc == 0:
        return n_proc, n_dev // n_proc
    g = 1
    for cand in range(int(math.isqrt(n_dev)), 0, -1):
        if n_dev % cand == 0:
            g = cand
            break
    return n_dev // g, g


def mesh_grid2d(cell_shards: int | None = None,
                gene_shards: int | None = None, devices=None) -> Mesh:
    """The (cells, genes) grid mesh over all global devices.

    ``jax.devices()`` lists process 0's chips first, so reshaping to
    (cell_shards, gene_shards) with one cell shard per host puts each
    host's chips in one grid ROW — the gene axis (and its per-pass
    O(rows x k) reductions) never leaves the host."""
    devices = list(jax.devices()) if devices is None else list(devices)
    n_dev = len(devices)
    if cell_shards is None and gene_shards is None:
        c, g = _grid_rc(n_dev, jax.process_count())
    else:
        if cell_shards is not None:
            c = int(cell_shards)
            g = n_dev // c if gene_shards is None else int(gene_shards)
        else:
            g = int(gene_shards)
            c = n_dev // g
        if c < 1 or g < 1 or c * g != n_dev:
            raise ValueError(
                f"grid {c}x{g} does not tile {n_dev} devices")
    return Mesh(np.asarray(devices).reshape(c, g), ("cells", "genes"))


# ---------------------------------------------------------------------------
# staging
# ---------------------------------------------------------------------------

def stage_x_grid(X, mesh: Mesh, dtype=jnp.float32, stats=None, events=None,
                 liveness=None):
    """Stage a host matrix (dense / CSR / :class:`ShardStore` /
    :class:`SlabCursor`) as (cells, genes) grid blocks.

    Rows stream one full-width ROW STRIPE at a time (the 1-D staging
    unit — host residency is one stripe, never the matrix), each stripe
    split into its per-device column tiles on host and uploaded through
    the pipelined streaming engine; store-backed inputs read only the
    slabs overlapping each addressable stripe. Returns
    ``(Xd (n_pad, g_pad) P('cells','genes'), row_pad, col_pad)`` —
    padding is exact zeros (benign: padded rows collapse their usage
    rows, padded gene columns are masked to exact zero in W at init and
    stay absorbing under every MU/Newton rate).
    """
    from ..runtime.faults import maybe_fail

    from .streaming import run_pipeline, stream_depth, stream_threads

    maybe_fail("upload", context="stage_x_grid")
    caxis, gaxis = mesh.axis_names
    c_dim, g_dim = (dict(mesh.shape)[caxis], dict(mesh.shape)[gaxis])

    if isinstance(X, SlabCursor):
        X = X.store
    if isinstance(X, ShardStore):
        n, g = X.shape
        store = X

        def read_rows(lo, hi):
            return store.row_block(lo, hi, events=events)
    elif sp.issparse(X):
        Xc = X.tocsr()
        n, g = Xc.shape

        def read_rows(lo, hi):
            return Xc[lo:hi]
    else:
        Xn = np.asarray(X)
        n, g = Xn.shape

        def read_rows(lo, hi):
            return Xn[lo:hi]

    n_pad = -(-max(n, 1) // c_dim) * c_dim
    g_pad = -(-max(g, 1) // g_dim) * g_dim
    rows_per = n_pad // c_dim
    cols_per = g_pad // g_dim
    sharding = NamedSharding(mesh, P(caxis, gaxis))
    idx_map = sharding.addressable_devices_indices_map((n_pad, g_pad))
    # group addressable devices by row stripe: one disk/host read serves
    # every column tile of the stripe
    stripes: dict = {}
    for dev, idx in idx_map.items():
        r0 = idx[0].start or 0
        c0 = idx[1].start or 0
        stripes.setdefault(r0, []).append((dev, c0))

    blocks: dict = {}
    stripe_bytes = rows_per * g_pad * 4

    def prep(r0):
        t0 = time.perf_counter()
        hi = min(r0 + rows_per, n)
        block = read_rows(r0, hi) if hi > r0 else None
        dense = np.zeros((rows_per, g_pad), np.float32)
        if block is not None:
            if sp.issparse(block):
                dense[:block.shape[0], :g] = block.toarray()
            else:
                dense[:block.shape[0], :g] = np.asarray(block,
                                                        np.float32)
        t1 = time.perf_counter()
        parts = {}
        for dev, c0 in stripes[r0]:
            tile = np.ascontiguousarray(dense[:, c0:c0 + cols_per])
            parts[dev] = jax.device_put(tile, dev)
        jax.block_until_ready(list(parts.values()))
        t2 = time.perf_counter()
        if stats is not None:
            stats.add(host_prep_s=t1 - t0, h2d_s=t2 - t1, slabs=1,
                      nbytes=stripe_bytes)
        return parts

    def commit(_r0, parts):
        blocks.update(parts)

    threads = stream_threads()
    depth = stream_depth(slab_bytes=stripe_bytes, threads=threads)
    t_wall = time.perf_counter()
    run_pipeline(sorted(stripes), prep, commit, depth=depth,
                 threads=threads, fault_context="stage_x_grid",
                 events=events, liveness=liveness)
    if stats is not None:
        stats.wall_s += time.perf_counter() - t_wall
    devs = list(idx_map)
    Xd = jax.make_array_from_single_device_arrays(
        (n_pad, g_pad), sharding, [blocks[d] for d in devs])
    return Xd, n_pad - n, g_pad - g


# ---------------------------------------------------------------------------
# overlapped axis-local reductions
# ---------------------------------------------------------------------------

def _tree_add(a, b):
    if a is None:
        return b
    return jax.tree_util.tree_map(jnp.add, a, b)


def _overlapped_psum(fn, nblk: int, axis: str, overlap: bool):
    """``Σ_b psum(fn(b, dep), axis)`` with block *b*'s collective
    dispatched while block *b+1*'s local contraction computes — the
    MPI-FAUN compute/communication overlap as a double-buffered,
    Python-unrolled loop (``nblk`` is static and small).

    ``fn(b, dep)`` returns a pytree of block-*b* partials and must fold
    the scalar ``dep`` into one of its operands (``x + dep`` — exact
    identity at ``dep == 0.0`` for the nonnegative factor state).
    ``overlap=False`` passes a zero DERIVED from block *b-1*'s reduced
    value instead of the literal ``0.0``: a true data dependence, so
    the scheduler cannot start gemm *b* before collective *b-1*
    completes — the serial baseline. Both modes accumulate the same
    partials in the same order, so their results are BIT-identical;
    only the scheduling freedom differs."""
    if nblk <= 1:
        return jax.tree_util.tree_map(
            lambda x: jax.lax.psum(x, axis), fn(0, jnp.float32(0.0)))
    acc = None
    prev = fn(0, jnp.float32(0.0))
    for b in range(1, nblk):
        red = jax.tree_util.tree_map(
            lambda x: jax.lax.psum(x, axis), prev)
        acc = _tree_add(acc, red)
        if overlap:
            dep = jnp.float32(0.0)
        else:
            first = jax.tree_util.tree_leaves(red)[0]
            dep = (first.ravel()[0] * jnp.float32(0.0)).astype(jnp.float32)
        prev = fn(b, dep)
    return _tree_add(acc, jax.tree_util.tree_map(
        lambda x: jax.lax.psum(x, axis), prev))


# ---------------------------------------------------------------------------
# grid-local update steps (run inside shard_map)
# ---------------------------------------------------------------------------

def _h_solve_grid(X_blk, h, W_blk, gaxis, beta, l1, l2, max_iter, h_tol,
                  kl_newton: bool, nblk: int, overlap: bool):
    """Tightly solve this cell stripe's usage block with W fixed — the
    grid twin of ``ops.nmf._chunk_h_solve``. W is gene-sharded, so the
    numerator-type statistics assemble from axis-local psums over the
    GENES axis (blocked + overlapped); the iteration itself (rates,
    rel-change stop) is local and bit-identical across the gene axis
    (every participant sees the same psum'd operands)."""
    g_loc = int(W_blk.shape[1])
    if nblk < 1 or g_loc % nblk:
        # a non-divisor block count would silently DROP the tail columns
        # from every psum'd statistic — fail at trace time instead
        # (grid_blocks() clamps to divisors; this guards direct callers)
        raise ValueError(
            f"nblk={nblk} does not divide the local gene extent {g_loc}")
    cb = g_loc // nblk

    def col(mat, b):
        return jax.lax.slice_in_dim(mat, b * cb, (b + 1) * cb, axis=1)

    if beta == 2.0:
        # loop-invariant statistics, one overlapped reduction each
        def stats(b, dep):
            Wb = col(W_blk, b) + dep
            return col(X_blk, b) @ Wb.T, Wb @ Wb.T

        numer0, WWT = _overlapped_psum(stats, nblk, gaxis, overlap)
        numer0 = jnp.maximum(numer0 - l1, 0.0) if l1 else numer0

        def step(h):
            denom = h @ WWT
            denom = denom + l2 * h if l2 else denom
            rate = jnp.where(denom < EPS, 0.0,
                             numer0 / jnp.maximum(denom, EPS))
            return h * rate
    elif kl_newton and beta == 1.0:
        # Diagonalized-Newton KL H step with the per-row monotone MU
        # fallback lane (ops/nmf.py:_dna_h_step) on grid statistics:
        # numerator/Hessian and the exact per-row candidate objectives
        # all psum over the genes axis
        s = jax.lax.psum(W_blk.sum(axis=1), gaxis)
        denom = jnp.broadcast_to(s[None, :], h.shape)

        def step(h):
            def stats(b, dep):
                Wb = col(W_blk, b) + dep
                WHb = jnp.maximum(h @ Wb, EPS)
                ratio = col(X_blk, b) / WHb
                return ratio @ Wb.T, (ratio / WHb) @ (Wb * Wb).T

            numer, hess = _overlapped_psum(stats, nblk, gaxis, overlap)
            H_mu = _apply_rate(h, numer, denom, l1, l2)
            grad = s[None, :] - numer + l1 + l2 * h
            H_nt = jnp.maximum(h - grad / jnp.maximum(hess + l2, EPS),
                               0.0)

            def objs(b, dep):
                Wb = col(W_blk, b) + dep
                Xb = col(X_blk, b)
                d_nt = -jnp.sum(
                    Xb * jnp.log(jnp.maximum(H_nt @ Wb, EPS)), axis=-1)
                d_mu = -jnp.sum(
                    Xb * jnp.log(jnp.maximum(H_mu @ Wb, EPS)), axis=-1)
                return d_nt, d_mu

            d_nt, d_mu = _overlapped_psum(objs, nblk, gaxis, overlap)
            o_nt = H_nt @ s + d_nt
            o_mu = H_mu @ s + d_mu
            if l1:
                o_nt = o_nt + l1 * jnp.sum(H_nt, axis=-1)
                o_mu = o_mu + l1 * jnp.sum(H_mu, axis=-1)
            if l2:
                o_nt = o_nt + 0.5 * l2 * jnp.sum(H_nt * H_nt, axis=-1)
                o_mu = o_mu + 0.5 * l2 * jnp.sum(H_mu * H_mu, axis=-1)
            return jnp.where((o_nt < o_mu)[:, None], H_nt, H_mu)
    else:  # plain MU, beta in {1, 0}
        if beta == 1.0:
            denom = jnp.broadcast_to(
                jax.lax.psum(W_blk.sum(axis=1), gaxis)[None, :], h.shape)

        def step(h):
            if beta == 1.0:
                def stats(b, dep):
                    Wb = col(W_blk, b) + dep
                    WHb = jnp.maximum(h @ Wb, EPS)
                    return (col(X_blk, b) / WHb) @ Wb.T

                numer = _overlapped_psum(stats, nblk, gaxis, overlap)
                return _apply_rate(h, numer, denom, l1, l2)

            def stats(b, dep):  # beta == 0.0 (itakura-saito)
                Wb = col(W_blk, b) + dep
                WHb = jnp.maximum(h @ Wb, EPS)
                return ((col(X_blk, b) / (WHb * WHb)) @ Wb.T,
                        (1.0 / WHb) @ Wb.T)

            numer, den = _overlapped_psum(stats, nblk, gaxis, overlap)
            return _apply_rate(h, numer, den, l1, l2,
                               gamma=mu_gamma(beta))

    def body(carry):
        h, _, it = carry
        h_new = step(h)
        rel = jnp.linalg.norm(h_new - h) / (jnp.linalg.norm(h) + EPS)
        return (h_new, rel, it + 1)

    def cond(carry):
        _, rel, it = carry
        return (it < max_iter) & (rel >= h_tol)

    rel0 = jnp.inf + 0.0 * jnp.sum(h)
    h, _, _ = jax.lax.while_loop(cond, body, (h, rel0, jnp.int32(0)))
    return h


def _w_update_grid(X_blk, h, W_blk, caxis, gaxis, beta, l1_W, l2_W,
                   max_iter, tol, nblk: int, overlap: bool):
    """The global W update from cells-axis-local statistics. beta=2
    solves the convex subproblem from the psum'd sufficient statistics
    ``A = Hᵀ X`` / ``B = Hᵀ H`` (returned for the checkpoint layer);
    beta in {1, 0} takes the exact MU step. The k x g_loc / k x k
    reductions here are the ONLY collectives that cross the cells axis
    (DCN on a pod) — O(k·(g+k)) bytes per pass, independent of cells."""
    rows = int(X_blk.shape[0])
    if nblk < 1 or rows % nblk:
        # same tail-dropping hazard as _h_solve_grid's column blocks
        raise ValueError(
            f"nblk={nblk} does not divide the local row extent {rows}")
    rb = rows // nblk

    def row(mat, b):
        return jax.lax.slice_in_dim(mat, b * rb, (b + 1) * rb, axis=0)

    A = B = None
    if beta == 2.0:
        def stats(b, dep):
            hb = row(h, b) + dep
            return hb.T @ row(X_blk, b), hb.T @ hb

        A, B = _overlapped_psum(stats, nblk, caxis, overlap)

        # the convex W subproblem from the statistics alone, with the
        # rel-change stop evaluated on the GLOBAL W (norms psum over the
        # gene axis) so every shard runs the same trip count and the
        # stopping rule matches ops.nmf._solve_w_from_stats
        def w_body(carry):
            W, _, it = carry
            W_new = _apply_rate(W, A, B @ W, l1_W, l2_W)
            d2 = jax.lax.psum(jnp.sum((W_new - W) ** 2), gaxis)
            n2 = jax.lax.psum(jnp.sum(W * W), gaxis)
            rel = jnp.sqrt(d2) / (jnp.sqrt(n2) + EPS)
            return (W_new, rel, it + 1)

        def w_cond(carry):
            _, rel, it = carry
            return (it < max_iter) & (rel >= tol)

        rel0 = jnp.inf + 0.0 * jnp.sum(W_blk)
        W_blk, _, _ = jax.lax.while_loop(
            w_cond, w_body, (W_blk, rel0, jnp.int32(0)))
        return W_blk, A, B
    if beta == 1.0:
        def stats(b, dep):
            hb = row(h, b) + dep
            WHb = jnp.maximum(hb @ W_blk, EPS)
            return hb.T @ (row(X_blk, b) / WHb)

        numer = _overlapped_psum(stats, nblk, caxis, overlap)
        denom = jnp.broadcast_to(
            jax.lax.psum(h.sum(axis=0), caxis)[:, None], W_blk.shape)
        return _apply_rate(W_blk, numer, denom, l1_W, l2_W), A, B

    def stats(b, dep):  # beta == 0.0 (itakura-saito)
        hb = row(h, b) + dep
        WHb = jnp.maximum(hb @ W_blk, EPS)
        return (hb.T @ (row(X_blk, b) / (WHb * WHb)),
                hb.T @ (1.0 / WHb))

    numer, denom = _overlapped_psum(stats, nblk, caxis, overlap)
    return _apply_rate(W_blk, numer, denom, l1_W, l2_W,
                       gamma=mu_gamma(beta)), A, B


def _grid_pass(X_blk, H, W_blk, caxis, gaxis, beta, h_tol, chunk_max_iter,
               l1_H, l2_H, l1_W, l2_W, kl_newton: bool, nblk_h: int,
               nblk_w: int, overlap: bool):
    """One block-coordinate pass on this grid tile: tight usage solve
    (genes-axis statistics), global W update (cells-axis statistics),
    objective of the updated pair (both axes). Returns
    ``(H, W_blk, err, A, B)`` — A/B are the beta=2 pass statistics for
    the checkpoint layer, None otherwise."""
    H = _h_solve_grid(X_blk, H, W_blk, gaxis, beta, l1_H, l2_H,
                      chunk_max_iter, h_tol, kl_newton, nblk_h, overlap)
    W_blk, A, B = _w_update_grid(X_blk, H, W_blk, caxis, gaxis, beta,
                                 l1_W, l2_W, chunk_max_iter, h_tol,
                                 nblk_w, overlap)
    err = jax.lax.psum(
        jax.lax.psum(_beta_div_dense(X_blk, H @ W_blk, beta), gaxis),
        caxis)
    return H, W_blk, err, A, B


def _grid_solve_local(X_blk, H, W_blk, caxis, gaxis, beta, tol, h_tol,
                      n_passes, chunk_max_iter, l1_H, l2_H, l1_W, l2_W,
                      telemetry: bool, kl_newton: bool, nblk_h: int,
                      nblk_w: int, overlap: bool):
    """Fused pass loop (runs inside shard_map) — same f32 convergence
    arithmetic and stopping rule as ``rowshard._rowsharded_solve_local``."""
    def one(H, W_blk, it):
        return _grid_pass(X_blk, H, W_blk, caxis, gaxis, beta, h_tol,
                          chunk_max_iter, l1_H, l2_H, l1_W, l2_W,
                          kl_newton, nblk_h, nblk_w, overlap)

    def body(carry):
        if telemetry:
            H, W_blk, err_prev, err, it, trace, nonfin = carry
        else:
            H, W_blk, err_prev, err, it = carry
        H, W_blk, err_new, _, _ = one(H, W_blk, it)
        if telemetry:
            trace = trace.at[jnp.minimum(it, TRACE_LEN - 1)].set(err_new)
            nonfin = nonfin | ~jnp.isfinite(err_new)
            return (H, W_blk, err, err_new, it + 1, trace, nonfin)
        return (H, W_blk, err, err_new, it + 1)

    def cond(carry):
        err_prev, err, it = carry[2], carry[3], carry[4]
        rel = (err_prev - err) / jnp.maximum(err_prev, EPS)
        return (it < n_passes) & ((it < 2) | (rel >= tol))

    H, W_blk, err0, _, _ = one(H, W_blk, jnp.int32(0))
    init = (H, W_blk, err0 * (1.0 + 2.0 * tol) + 1.0, err0, jnp.int32(1))
    if telemetry:
        init = init + (jnp.full((TRACE_LEN,), jnp.nan,
                                jnp.float32).at[0].set(err0),
                       ~jnp.isfinite(err0))
    out = jax.lax.while_loop(cond, body, init)
    if telemetry:
        H, W_blk, _, err, it, trace, nonfin = out
        return H, W_blk, err, trace, it, nonfin | ~jnp.isfinite(err)
    H, W_blk, _, err, _ = out
    return H, W_blk, err


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "beta", "n_passes", "chunk_max_iter",
                     "l1_H", "l2_H", "l1_W", "l2_W", "telemetry",
                     "kl_newton", "nblk_h", "nblk_w", "overlap"),
)
def _fit_grid2d_jit(X, H0, W0, mesh, beta, tol, h_tol, n_passes,
                    chunk_max_iter, l1_H, l2_H, l1_W, l2_W,
                    telemetry: bool = False, kl_newton: bool = False,
                    nblk_h: int = 1, nblk_w: int = 1,
                    overlap: bool = True):
    caxis, gaxis = mesh.axis_names
    out_specs = ((P(caxis, None), P(None, gaxis), P())
                 if not telemetry
                 else (P(caxis, None), P(None, gaxis), P(), P(), P(),
                       P()))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(caxis, gaxis), P(caxis, None), P(None, gaxis)),
        out_specs=out_specs,
    )
    def run(X_blk, H, W_blk):
        out = _grid_solve_local(
            X_blk, H, W_blk, caxis, gaxis, beta, tol, h_tol, n_passes,
            chunk_max_iter, l1_H, l2_H, l1_W, l2_W, telemetry,
            kl_newton, nblk_h, nblk_w, overlap)
        if telemetry:
            H, W_blk, err, trace, passes, nonfin = out
            return (H, W_blk, err[None], trace, passes[None],
                    nonfin[None])
        H, W_blk, err = out
        return H, W_blk, err[None]

    out = run(X, H0, W0)
    if telemetry:
        H, W, err, trace, passes, nonfin = out
        return H, W, err[0], trace, passes[0], nonfin[0]
    H, W, err = out
    return H, W, err[0]


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "beta", "chunk_max_iter", "l1_H", "l2_H",
                     "l1_W", "l2_W", "kl_newton", "nblk_h", "nblk_w",
                     "overlap"),
)
def _grid_pass_jit(X, H, W, mesh, beta, h_tol, chunk_max_iter,
                   l1_H, l2_H, l1_W, l2_W, kl_newton: bool = False,
                   nblk_h: int = 1, nblk_w: int = 1,
                   overlap: bool = True):
    """ONE grid pass as its own dispatch — the unit of the checkpointed
    host-driven loop. The per-tile program is exactly the fused loop's
    pass body. Returns ``(H, W, err, A, B)`` (A/B None for beta != 2)."""
    caxis, gaxis = mesh.axis_names
    with_stats = beta == 2.0
    out_specs = ((P(caxis, None), P(None, gaxis), P(), P(None, gaxis),
                  P()) if with_stats
                 else (P(caxis, None), P(None, gaxis), P()))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(caxis, gaxis), P(caxis, None), P(None, gaxis)),
        out_specs=out_specs,
    )
    def run(X_blk, H_loc, W_blk):
        H_loc, W_blk, err, A, B = _grid_pass(
            X_blk, H_loc, W_blk, caxis, gaxis, beta, h_tol,
            chunk_max_iter, l1_H, l2_H, l1_W, l2_W, kl_newton, nblk_h,
            nblk_w, overlap)
        if with_stats:
            return H_loc, W_blk, err[None], A, B
        return H_loc, W_blk, err[None]

    out = run(X, H, W)
    if with_stats:
        H, W, err, A, B = out
        return H, W, err[0], A, B
    H, W, err = out
    return H, W, err[0], None, None


def _fit_grid2d_checkpointed(Xd, H0, W0, mesh, beta, tol, h_tol, n_passes,
                             chunk_max_iter, l1_H, l2_H, l1_W, l2_W, ckpt,
                             heartbeat=None, n_orig=None, g_orig=None,
                             kl_newton: bool = False, nblk_h: int = 1,
                             nblk_w: int = 1, overlap: bool = True):
    """Host-driven grid pass loop with mid-run checkpoints — the grid
    twin of ``rowshard._fit_rowsharded_checkpointed`` (same f32
    convergence arithmetic, same PassCheckpointer contract: W and the
    beta=2 (A, B) statistics persist TRIMMED to the true gene width —
    padded columns are exact zeros, so re-padding on a resumed mesh
    with a different gene-shard count is exact — and H rides under the
    byte budget). Heartbeat stamps + the ``hostloss`` chaos hook fire
    at every pass boundary, so the elastic controller can re-plan the
    grid over survivors and re-enter with ``resume=True``."""
    from ..runtime.faults import maybe_hostloss

    caxis, gaxis = mesh.axis_names
    row_sh = NamedSharding(mesh, P(caxis, None))
    w_sh = NamedSharding(mesh, P(None, gaxis))
    k = int(W0.shape[0])
    g_pad = int(W0.shape[1])
    g = int(g_orig) if g_orig is not None else g_pad
    n_pad = int(Xd.shape[0])
    h_tol_j = jnp.float32(h_tol)
    f32 = np.float32

    def one_pass(H, W):
        return _grid_pass_jit(
            Xd, H, W, mesh, beta, h_tol_j, int(chunk_max_iter),
            l1_H, l2_H, l1_W, l2_W, kl_newton=kl_newton, nblk_h=nblk_h,
            nblk_w=nblk_w, overlap=overlap)

    def _pad_w(w_np):
        w_np = np.asarray(w_np, np.float32)[:, :g]
        if w_np.shape[1] < g_pad:
            w_np = np.pad(w_np, ((0, 0), (0, g_pad - w_np.shape[1])))
        return w_np

    trace = np.full((TRACE_LEN,), np.nan, np.float32)
    A = B = None
    ran_pass = False

    state = (ckpt.load(n_rows_min=int(n_orig), n_genes=g)
             if n_orig is not None else ckpt.load(n_rows=n_pad, n_genes=g))
    if state is not None:
        W = jax.device_put(jnp.asarray(_pad_w(state["W"])), w_sh)
        if state["H"] is not None:
            h_np = np.asarray(state["H"], np.float32)
            if h_np.shape[0] > n_pad:
                h_np = h_np[:n_pad]
            elif h_np.shape[0] < n_pad:
                h_np = np.pad(h_np, ((0, n_pad - h_np.shape[0]), (0, 0)))
            H = jax.device_put(jnp.asarray(h_np), row_sh)
        else:
            H = H0
        resumed_without_h = state["H"] is None
        it = int(state["pass_idx"])
        err_prev, err = f32(state["err_prev"]), f32(state["err"])
        n_tr = min(len(state["trace"]), TRACE_LEN)
        trace[:n_tr] = state["trace"][:n_tr]
        A, B = state["A"], state["B"]
    else:
        resumed_without_h = False
        H, W, err0, A, B = one_pass(H0, W0)
        ran_pass = True
        err = f32(err0)
        err_prev = f32(err * f32(1.0 + 2.0 * tol) + f32(1.0))
        it = 1
        trace[0] = err

    def _save():
        h_np = (np.asarray(H) if n_pad * k * 4 <= ckpt.h_budget else None)
        ckpt.save(pass_idx=it, err_prev=err_prev, err=err, trace=trace,
                  W=np.asarray(W)[:, :g],
                  A=(np.asarray(A)[:, :g] if A is not None
                     else np.zeros((k, g), np.float32)),
                  B=(np.asarray(B) if B is not None
                     else np.zeros((k, k), np.float32)),
                  H=h_np)

    def _pass_boundary():
        if heartbeat is not None:
            heartbeat.beat(phase="pass", cursor=it)
        maybe_hostloss(context="pass")

    if ran_pass and ckpt.every and it % ckpt.every == 0 and ckpt.due():
        _save()
    _pass_boundary()

    def active() -> bool:
        if it >= int(n_passes):
            return False
        if it < 2:
            return True
        rel = (f32(err_prev) - f32(err)) / max(f32(err_prev), f32(EPS))
        return bool(rel >= f32(tol))

    while active():
        H, W, err_new, A, B = one_pass(H, W)
        ran_pass = True
        err_prev, err = err, f32(err_new)
        it += 1
        trace[min(it - 1, TRACE_LEN - 1)] = err
        if ckpt.every and it % ckpt.every == 0 and ckpt.due():
            _save()
        _pass_boundary()

    if resumed_without_h and not ran_pass:
        # already-converged checkpoint without H: re-derive usages from
        # the final W with one fixed-W grid solve (W untouched)
        H = _fit_h_grid_jit(Xd, H0, W, mesh, beta, int(chunk_max_iter),
                            h_tol_j, l1_H, l2_H, kl_newton, nblk_h,
                            overlap)
    nonfin = not bool(np.isfinite(f32(err)))
    return H, W, float(err), trace, it, nonfin


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "beta", "chunk_max_iter", "l1_H", "l2_H",
                     "kl_newton", "nblk_h", "overlap"),
)
def _fit_h_grid_jit(X, H0, W, mesh, beta, chunk_max_iter, h_tol,
                    l1_H, l2_H, kl_newton: bool = False, nblk_h: int = 1,
                    overlap: bool = True):
    caxis, gaxis = mesh.axis_names
    fn = shard_map(
        lambda x, h, w: _h_solve_grid(x, h, w, gaxis, beta, l1_H, l2_H,
                                      chunk_max_iter, h_tol, kl_newton,
                                      nblk_h, overlap),
        mesh=mesh,
        in_specs=(P(caxis, gaxis), P(caxis, None), P(None, gaxis)),
        out_specs=P(caxis, None))
    return fn(X, H0, W)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

def _coll_bytes_per_pass(rows_loc, g_loc, k, beta, nblk_h, nblk_w,
                         n_dev) -> int:
    """Logical per-pass psum payload bytes (summed over devices) for the
    pass-level statistics reductions — the H-side hoists/first iteration
    plus the W-side sufficient statistics. KL/IS inner iterations add
    one H-side round per iteration (not counted here; the telemetry
    context records the loss so readers can scale)."""
    if beta == 2.0:
        h_side = nblk_h * (rows_loc * k + k * k)
        w_side = nblk_w * (k * g_loc + k * k)
    else:
        h_side = nblk_h * rows_loc * k + k  # first iteration + colsum
        w_side = nblk_w * k * g_loc + k
    return int((h_side + w_side + 1) * 4 * n_dev)


def nmf_fit_grid2d(X, k: int, mesh: Mesh, beta_loss="frobenius",
                   seed: int = 0, tol: float = 1e-4, h_tol: float = 0.05,
                   n_passes: int | None = None, chunk_max_iter: int = 1000,
                   alpha_W: float = 0.0, l1_ratio_W: float = 0.0,
                   alpha_H: float = 0.0, l1_ratio_H: float = 0.0,
                   n_orig: int | None = None, g_orig: int | None = None,
                   init: str = "random", telemetry_sink=None,
                   checkpoint=None, heartbeat=None, recipe=None,
                   events=None):
    """Factorize X over the 2-D (cells x genes) grid ``mesh``. Returns
    ``(H (n, k), W (k, g), err)`` as numpy arrays — the same contract,
    recipe/checkpoint/heartbeat/hostloss hooks, and telemetry payload
    shape as :func:`~cnmf_torch_tpu.parallel.rowshard.nmf_fit_rowsharded`
    (mode ``grid2d``).

    ``X`` may be a host matrix (dense/CSR — staged stripe-wise, no host
    dense copy), a :class:`ShardStore` (each process reads only the
    slabs overlapping its addressable cell stripes), or a device array
    already staged by :func:`stage_x_grid` (pass ``n_orig``/``g_orig``).
    Supported recipes: plain MU (beta in {2, 1, 0}) and the
    Diagonalized-Newton KL lane (``kl_newton``); the sketch recipe has
    no grid lane and raises.
    """
    beta = beta_loss_to_float(beta_loss)
    _, n_passes, _ = resolve_online_schedule(beta, h_tol, n_passes)
    if beta not in (2.0, 1.0, 0.0):
        raise ValueError(
            f"nmf_fit_grid2d supports beta in {{2, 1, 0}}, got {beta}")
    if init != "random":
        raise ValueError(
            f"nmf_fit_grid2d requires init='random', got {init!r} (the "
            "nndsvd gram base is not sharded over the gene axis)")
    caxis, gaxis = mesh.axis_names
    c_dim, g_dim = (dict(mesh.shape)[caxis], dict(mesh.shape)[gaxis])

    if isinstance(X, jax.Array):
        Xd = X
        if n_orig is None:
            n_orig = int(X.shape[0])
        if g_orig is None:
            g_orig = int(X.shape[1])
    else:
        n_orig = int(X.shape[0]) if n_orig is None else n_orig
        g_orig = int(X.shape[1]) if g_orig is None else g_orig
        Xd, _, _ = stage_x_grid(X, mesh, events=events,
                                liveness=heartbeat)
    n_pad, g_pad = int(Xd.shape[0]), int(Xd.shape[1])

    if recipe is None:
        from ..ops.recipe import resolve_recipe

        recipe = resolve_recipe(beta, "rowshard", algo="mu", ell=False,
                                n=int(n_orig), g=int(g_orig), k=int(k))
    if recipe.kl_newton and beta != 1.0:
        raise ValueError(
            f"recipe {recipe.label!r} requires beta=1 (KL), got "
            f"beta={beta}")
    if recipe.algo == "sketch":
        raise ValueError(
            "the sketch recipe has no (cells x genes) grid lane — run "
            "the 1-D rowshard path, or pin CNMF_TPU_SKETCH=0 for grid2d")
    kl_newton = bool(recipe.kl_newton)
    # fused Pallas KL kernels (ISSUE 16) have no grid lane: the 2-D grid
    # stages dense gene stripes (no ELL encoding), so the knob is merely
    # consulted — bad knob words fail as loudly here as on the ELL paths,
    # and a forced =1 run still compiles the bit-identical dense pass
    # programs — and the records carry the literal dense kernel label
    from ..ops.pallas import resolve_pallas

    resolve_pallas()
    kernel = "dense-jnp"

    key = jax.random.key(int(seed) & 0x7FFFFFFF)
    x_mean = jnp.sum(Xd) / (n_pad * g_pad)
    H0, W0 = random_init(key, n_pad, g_pad, int(k), x_mean)
    # padded gene columns masked to EXACT zero: a zero W column is
    # absorbing under every rate here, contributes exact +0.0 to the
    # H-side statistics (its X column is zero-padded too), and lets the
    # checkpoint trim/re-pad W exactly across re-meshes
    if g_pad > g_orig:
        W0 = W0 * (jnp.arange(g_pad) < g_orig)[None, :]
    H0 = jax.device_put(H0, NamedSharding(mesh, P(caxis, None)))
    W0 = jax.device_put(W0, NamedSharding(mesh, P(None, gaxis)))

    l1_W, l2_W = split_regularization(alpha_W, l1_ratio_W)
    l1_H, l2_H = split_regularization(alpha_H, l1_ratio_H)

    rows_loc = n_pad // c_dim
    g_loc = g_pad // g_dim
    overlap = grid_overlap_enabled()
    nblk_h = grid_blocks(g_loc)
    nblk_w = grid_blocks(rows_loc)

    want_telem = False
    if telemetry_sink is not None:
        from ..utils.telemetry import telemetry_enabled

        want_telem = telemetry_enabled()

    t0 = time.perf_counter()
    if checkpoint is not None and getattr(checkpoint, "every", 0) > 0:
        H, W, err, trace_np, passes, nonfin = _fit_grid2d_checkpointed(
            Xd, H0, W0, mesh, beta, float(tol), float(h_tol),
            int(n_passes), int(chunk_max_iter), l1_H, l2_H, l1_W, l2_W,
            checkpoint, heartbeat=heartbeat, n_orig=n_orig,
            g_orig=g_orig, kl_newton=kl_newton, nblk_h=nblk_h,
            nblk_w=nblk_w, overlap=overlap)
        trace_arr, iters_run = trace_np, passes
        nonfin_flag = nonfin
    else:
        out = _fit_grid2d_jit(
            Xd, H0, W0, mesh, beta, jnp.float32(tol),
            jnp.float32(h_tol), int(n_passes), int(chunk_max_iter),
            l1_H, l2_H, l1_W, l2_W, telemetry=want_telem,
            kl_newton=kl_newton, nblk_h=nblk_h, nblk_w=nblk_w,
            overlap=overlap)
        H, W, err = out[:3]
        if want_telem:
            trace_arr, iters_run, nonfin_flag = out[3:]
        else:
            trace_arr = iters_run = nonfin_flag = None
    jax.block_until_ready(W)
    wall = time.perf_counter() - t0

    if jax.process_count() > 1:
        # H is cells-sharded across hosts and W gene-sharded within
        # them — neither is fully addressable on a pod, so every host
        # gathers (each needs the full factors for artifacts anyway)
        from jax.experimental import multihost_utils

        H_np = np.asarray(
            multihost_utils.process_allgather(H, tiled=True))[:n_orig]
        W_np = np.asarray(
            multihost_utils.process_allgather(W, tiled=True))[:, :g_orig]
    else:
        H_np = np.asarray(H)[:n_orig]
        W_np = np.asarray(W)[:, :g_orig]
    err_f = float(np.asarray(err))
    if want_telem:
        telemetry_sink({
            "k": int(k), "beta": float(beta), "mode": "grid2d",
            "seeds": [int(seed)], "cap": int(n_passes),
            "cadence": "pass",
            "trace": np.asarray(trace_arr)[None],
            "iters": np.asarray([int(np.asarray(iters_run))]),
            "nonfinite": np.asarray([bool(np.asarray(nonfin_flag))]),
            "errs": np.asarray([err_f], np.float64),
            "recipe": recipe.label, "kernel": kernel})
    if events is not None and getattr(events, "enabled", False):
        n_dev = c_dim * g_dim
        passes_run = (int(np.asarray(iters_run))
                      if iters_run is not None else None)
        events.emit(
            "collective",
            context={"stage": "grid2d_pass_stats", "k": int(k),
                     "beta": float(beta),
                     "mesh_shape": [int(c_dim), int(g_dim)],
                     "blocks": [int(nblk_h), int(nblk_w)],
                     "overlap": bool(overlap),
                     "kernel": kernel,
                     "passes": passes_run},
            wall_s=round(wall, 4),
            nbytes=_coll_bytes_per_pass(rows_loc, g_loc, int(k), beta,
                                        nblk_h, nblk_w, n_dev),
            overlap_fraction=None)
    return H_np, W_np, err_f


# ---------------------------------------------------------------------------
# collective-wall / overlap measurement (bench + telemetry probe)
# ---------------------------------------------------------------------------

@functools.partial(
    jax.jit,
    static_argnames=("mesh", "rows_loc", "g_loc", "k", "nblk_h",
                     "nblk_w", "chained", "beta"),
)
def _collective_probe_jit(x, mesh, rows_loc: int, g_loc: int, k: int,
                          nblk_h: int, nblk_w: int,
                          chained: bool = False, beta: float = 2.0):
    """Collectives-only probe: the psum schedule of ONE pass-level
    statistics round for this ``beta`` (matching
    :func:`_coll_bytes_per_pass` — beta=2: blocked (rows, k)/(k, k)
    H-side + (k, g_loc)/(k, k) W-side; beta in {1, 0}: blocked
    (rows, k) H-side + the (k,) colsum hoist, blocked (k, g_loc)
    W-side + the (k,) row-sum — KL/IS additionally repeat the H-side
    round per inner iteration, which this floor deliberately does not
    model), on zero payloads derived from a tiny input (so the program
    is not constant-folded).

    ``chained=False`` leaves every reduce independent — the scheduler
    may overlap their rendezvous latencies, exactly the freedom the
    double-buffered pass gives its collectives. ``chained=True``
    data-chains each reduce's input on the previous reduce's output —
    the serial-baseline structure, one rendezvous fully paid per
    block. Timing the two isolates the latency-hiding the overlap
    dispatch buys on the collective wall itself."""
    caxis, gaxis = mesh.axis_names
    with_kk = beta == 2.0

    @functools.partial(shard_map, mesh=mesh, in_specs=(P(),),
                       out_specs=P())
    def run(z):
        zero = z[0] * 0.0
        acc = zero
        dep = zero
        for _ in range(nblk_h):
            fill = zero + dep if chained else zero
            a = jax.lax.psum(jnp.full((rows_loc, k), fill), gaxis)
            dep = a[0, 0] * 0.0
            acc = acc + a[0, 0]
            if with_kk:
                acc = acc + jax.lax.psum(jnp.full((k, k), zero),
                                         gaxis)[0, 0]
        if not with_kk:  # the hoisted KL/IS colsum denominator
            acc = acc + jax.lax.psum(jnp.full((k,), zero), gaxis)[0]
        for _ in range(nblk_w):
            fill = zero + dep if chained else zero
            a = jax.lax.psum(jnp.full((k, g_loc), fill), caxis)
            dep = a[0, 0] * 0.0
            acc = acc + a[0, 0]
            if with_kk:
                acc = acc + jax.lax.psum(jnp.full((k, k), zero),
                                         caxis)[0, 0]
        if not with_kk:  # the KL W-step's psum'd H row-sum
            acc = acc + jax.lax.psum(jnp.full((k,), zero), caxis)[0]
        return jnp.asarray([acc])

    return run(x)


def measure_collectives(Xd, k: int, mesh: Mesh, beta: float = 2.0,
                        h_tol: float = 0.05, chunk_max_iter: int = 50,
                        seed: int = 0, repeats: int = 11) -> dict:
    """Measure the statistics-collective wall and the overlap fraction
    on a STAGED grid array.

    Two measurements, reported together:

      * ``overlap_fraction`` — collective-level latency hiding:
        the per-pass psum schedule timed with every reduce independent
        (the double-buffered dispatch's structure — rendezvous
        latencies overlap) vs data-chained (the serial baseline's
        structure — each reduce fully paid), interleaved sampling,
        ``max(0, (chained - free) / chained)`` over medians. This is
        the structural quantity: it measures what the overlapped
        dispatch is free to hide, stable even on oversubscribed
        single-host CPU simulation.
      * ``pass_hidden_fraction`` — end-to-end: one full pass compiled
        with the overlap vs with the serializing barrier (bit-identical
        math), as a fraction of the collective wall. On real multi-chip
        hardware this converges to the fraction of the collective wall
        off the critical path; on a CPU host whose simulated devices
        timeshare one core, blocked rendezvous waits cost no CPU, so
        the true value is ~0 and the report says so honestly.

    Returns ``{coll_chained_s, coll_free_s, overlap_fraction,
    pass_overlap_s, pass_serial_s, pass_hidden_fraction, blocks,
    nbytes_per_pass}``."""
    caxis, gaxis = mesh.axis_names
    c_dim, g_dim = (dict(mesh.shape)[caxis], dict(mesh.shape)[gaxis])
    n_pad, g_pad = int(Xd.shape[0]), int(Xd.shape[1])
    rows_loc, g_loc = n_pad // c_dim, g_pad // g_dim
    nblk_h, nblk_w = grid_blocks(g_loc), grid_blocks(rows_loc)

    key = jax.random.key(int(seed) & 0x7FFFFFFF)
    x_mean = jnp.sum(Xd) / (n_pad * g_pad)
    H0, W0 = random_init(key, n_pad, g_pad, int(k), x_mean)
    H0 = jax.device_put(H0, NamedSharding(mesh, P(caxis, None)))
    W0 = jax.device_put(W0, NamedSharding(mesh, P(None, gaxis)))
    h_tol_j = jnp.float32(h_tol)

    def one_pass(overlap):
        out = _grid_pass_jit(Xd, H0, W0, mesh, float(beta), h_tol_j,
                             int(chunk_max_iter), 0.0, 0.0, 0.0, 0.0,
                             nblk_h=nblk_h, nblk_w=nblk_w,
                             overlap=overlap)
        jax.block_until_ready(out[1])

    probe_in = jax.device_put(jnp.ones((1,), jnp.float32),
                              NamedSharding(mesh, P()))

    def coll_only(chained):
        jax.block_until_ready(_collective_probe_jit(
            probe_in, mesh, rows_loc, g_loc, int(k), nblk_h, nblk_w,
            chained=chained, beta=float(beta)))

    reps = max(int(repeats), 1)

    def timed_pair(fn_a, fn_b):
        # interleaved A/B sampling cancels slow host drift; medians of
        # each stream are compared
        fn_a()
        fn_b()  # compile / warm both
        wa, wb = [], []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn_a()
            wa.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            fn_b()
            wb.append(time.perf_counter() - t0)
        return float(np.median(wa)), float(np.median(wb))

    t_chain, t_free = timed_pair(lambda: coll_only(True),
                                 lambda: coll_only(False))
    t_ser, t_ovl = timed_pair(lambda: one_pass(False),
                              lambda: one_pass(True))
    frac = (max(0.0, (t_chain - t_free) / t_chain)
            if t_chain > 0 else 0.0)
    pass_frac = (min(1.0, max(0.0, t_ser - t_ovl) / t_chain)
                 if t_chain > 0 else 0.0)
    return {
        "coll_chained_s": round(t_chain, 6),
        "coll_free_s": round(t_free, 6),
        "overlap_fraction": round(frac, 4),
        "pass_overlap_s": round(t_ovl, 6),
        "pass_serial_s": round(t_ser, 6),
        "pass_hidden_fraction": round(pass_frac, 4),
        "blocks": [int(nblk_h), int(nblk_w)],
        "nbytes_per_pass": _coll_bytes_per_pass(
            rows_loc, g_loc, int(k), float(beta), nblk_h, nblk_w,
            c_dim * g_dim),
    }


# ---------------------------------------------------------------------------
# analytic cost hooks (ISSUE 19, obs/costmodel.py)
# ---------------------------------------------------------------------------

def grid_pass_cost(rows_loc: int, g_loc: int, k: int, beta: float = 2.0,
                   *, nblk_h: int = 1, nblk_w: int = 1,
                   n_dev: int = 4) -> dict:
    """Analytic PER-DEVICE flop/byte cost of one :func:`_grid_pass_jit`
    beta=2 pass, in XLA ``cost_analysis()`` accounting (while-loop
    bodies counted once, per the trip-count-1 convention XLA uses for
    dynamic loops). Byte constants are calibrated against XLA CPU's
    buffer accounting for this program (least-squares over 8 pinned
    shapes, residual < 0.1%). collective_bytes uses the same formula
    the live `collective` telemetry event reports
    (:func:`_coll_bytes_per_pass`). Host arithmetic only.
    """
    r, gl, k = int(rows_loc), int(g_loc), int(k)
    if beta == 2.0:
        flops = (
            k * gl + 2 * r * gl * k + 2 * k * gl * k + 2 * (r * k + k * k)
            + 2 * r * k * k + 4 * r * k + 3 * r * k + 4
            + r * k + 2 * r * gl * k + 2 * r * k * k + 2 * (gl * k + k * k)
            + 2 * k * k * gl + 4 * k * gl + 4 * k * gl + 4
            + 2 * r * gl * k + 3 * r * gl + 2)
        bytes_ = (4.0 * (7 * r * gl + 26 * (r * k + k * gl) + 8 * k * k)
                  + 0.75 * (r + gl) + 402.0)
    else:
        # KL/IS passes share the stats shapes but run ratio chains over
        # the local X block; approximate with the dominant terms (no
        # calibrated fit — flagged approximate by the cost model).
        flops = (8 * r * gl * k + 6 * r * gl
                 + 4 * r * k * k + 4 * k * k * gl + 7 * (r * k + k * gl))
        bytes_ = 4.0 * (9 * r * gl + 26 * (r * k + k * gl) + 8 * k * k)
    coll = _coll_bytes_per_pass(r, gl, k, float(beta),
                                int(nblk_h), int(nblk_w), int(n_dev))
    return {"flops": float(flops), "bytes": float(bytes_),
            "collective_bytes": float(coll),
            "calibrated": beta == 2.0, "lane": "grid2d"}


def coll_bytes_per_pass(rows_loc, g_loc, k, beta, nblk_h, nblk_w, n_dev):
    """Public alias of :func:`_coll_bytes_per_pass` for obs/costmodel."""
    return _coll_bytes_per_pass(rows_loc, g_loc, k, beta,
                                nblk_h, nblk_w, n_dev)
