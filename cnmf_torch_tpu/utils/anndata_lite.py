"""Minimal AnnData-compatible container with h5ad (HDF5) persistence.

The reference pipeline stores every matrix-shaped intermediate as an ``.h5ad``
AnnData file written by scanpy (``/root/reference/src/cnmf/cnmf.py:545, 698``).
The ``anndata``/``scanpy`` packages are not dependencies of this framework, so
this module provides a small, spec-conformant subset of the AnnData on-disk
format (v0.1.0 "anndata" encoding): enough for real anndata to read our files
and for us to read files written by anndata/scanpy (dense or CSR/CSC ``X``,
``obs``/``var`` dataframes with string / numeric / categorical columns).

Only the features the cNMF pipeline needs are implemented: ``X``, ``obs``,
``var``, name-based and boolean column/row subsetting, and copy semantics.
"""

from __future__ import annotations

import contextlib
import os

import numpy as np
import pandas as pd
import scipy.sparse as sp

__all__ = ["AnnDataLite", "read_h5ad", "write_h5ad", "atomic_artifact"]


@contextlib.contextmanager
def atomic_artifact(filename):
    """Crash-safe artifact write: yield a same-directory temp path for the
    caller to write, then ``os.replace`` it onto ``filename`` — readers
    see either the old complete file or the new complete file, never a
    torn intermediate (the invariant ``--skip-completed-runs`` and
    ``combine`` rely on). A SIGKILL mid-write costs only an orphaned
    pid-suffixed temp file — never picked up by any reader, and swept by
    the launcher's ``--clean`` pass (a successor process has a different
    pid, so it does NOT overwrite the orphan). On any exception the temp
    file is removed and nothing is renamed."""
    filename = os.fspath(filename)
    tmp = filename + ".tmp-%d" % os.getpid()
    try:
        yield tmp
        os.replace(tmp, filename)
    finally:
        with contextlib.suppress(OSError):
            os.unlink(tmp)


class AnnDataLite:
    """cells x genes matrix with obs (cell) and var (gene) annotations.

    Mirrors the subset of :class:`anndata.AnnData` used by the reference
    pipeline (construction from ``X``/``obs``/``var``, ``adata[:, genes]``
    subsetting at ``cnmf.py:670``, ``adata.X`` mutation, ``.copy()``).
    """

    def __init__(self, X, obs: pd.DataFrame | None = None, var: pd.DataFrame | None = None,
                 obsm: dict | None = None):
        if sp.issparse(X):
            X = X.tocsr()
        else:
            X = np.asarray(X)
        self.X = X
        n, g = X.shape
        if obs is None:
            obs = pd.DataFrame(index=pd.Index([str(i) for i in range(n)]))
        if var is None:
            var = pd.DataFrame(index=pd.Index([str(i) for i in range(g)]))
        if len(obs.index) != n:
            raise ValueError(f"obs has {len(obs.index)} rows but X has {n}")
        if len(var.index) != g:
            raise ValueError(f"var has {len(var.index)} rows but X has {g}")
        self.obs = obs
        self.var = var
        self.obsm = dict(obsm) if obsm else {}

    # -- basic protocol ----------------------------------------------------
    @property
    def shape(self):
        return self.X.shape

    @property
    def n_obs(self):
        return self.X.shape[0]

    @property
    def n_vars(self):
        return self.X.shape[1]

    @property
    def obs_names(self) -> pd.Index:
        return self.obs.index

    @property
    def var_names(self) -> pd.Index:
        return self.var.index

    def copy(self) -> "AnnDataLite":
        return AnnDataLite(self.X.copy(), self.obs.copy(), self.var.copy(),
                           {k: np.array(v) for k, v in self.obsm.items()})

    def var_names_make_unique(self, join: str = "-"):
        """Deduplicate var names anndata-style: later occurrences of a
        repeated name get ``name{join}{i}`` suffixes (i = 1, 2, ...)."""
        names = list(self.var.index.astype(str))
        existing = set(names)
        seen: dict[str, int] = {}
        out = []
        for name in names:
            if name in seen:
                # re-check candidates against every name so a suffixed name
                # never collides with a pre-existing one (anndata semantics:
                # ['GENE', 'GENE-1', 'GENE'] -> ['GENE', 'GENE-1', 'GENE-2'])
                i = seen[name] + 1
                while f"{name}{join}{i}" in existing:
                    i += 1
                seen[name] = i
                cand = f"{name}{join}{i}"
                existing.add(cand)
                out.append(cand)
            else:
                seen[name] = 0
                out.append(name)
        self.var.index = pd.Index(out)

    def _resolve_idx(self, key, index: pd.Index, axis_len: int):
        """Convert a row/column selector into a positional indexer."""
        if isinstance(key, slice):
            return key
        key = np.asarray(key) if not np.isscalar(key) else np.asarray([key])
        if key.dtype == bool:
            if key.shape[0] != axis_len:
                raise IndexError("boolean mask length mismatch")
            return np.where(key)[0]
        if key.dtype.kind in "iu":
            return key
        # name-based lookup (list of obs/var names)
        locs = index.get_indexer(pd.Index(key))
        if (locs < 0).any():
            missing = list(pd.Index(key)[locs < 0][:5])
            raise KeyError(f"names not found in axis: {missing}")
        return locs

    def __getitem__(self, key) -> "AnnDataLite":
        if not isinstance(key, tuple):
            key = (key, slice(None))
        rows = self._resolve_idx(key[0], self.obs.index, self.n_obs)
        cols = self._resolve_idx(key[1], self.var.index, self.n_vars)
        X = self.X[rows, :][:, cols]
        obsm = {k: np.asarray(v)[rows] for k, v in self.obsm.items()}
        return AnnDataLite(X, self.obs.iloc[rows], self.var.iloc[cols], obsm)

    def __repr__(self):
        kind = "sparse" if sp.issparse(self.X) else "dense"
        return f"AnnDataLite(n_obs={self.n_obs}, n_vars={self.n_vars}, X={kind})"

    def write(self, filename: str):
        write_h5ad(filename, self)


# -- h5ad persistence ------------------------------------------------------

def _str_dtype():
    import h5py

    return h5py.string_dtype(encoding="utf-8")


def _write_string_array(group, name, values):
    ds = group.create_dataset(name, data=np.asarray(values, dtype=object), dtype=_str_dtype())
    ds.attrs["encoding-type"] = "string-array"
    ds.attrs["encoding-version"] = "0.2.0"
    return ds


def _write_dataframe(parent, name: str, df: pd.DataFrame):
    g = parent.create_group(name)
    g.attrs["encoding-type"] = "dataframe"
    g.attrs["encoding-version"] = "0.2.0"
    index_name = df.index.name or "_index"
    g.attrs["_index"] = index_name
    g.attrs["column-order"] = np.asarray(list(df.columns), dtype=object) if len(df.columns) else np.asarray([], dtype=_str_dtype())
    _write_string_array(g, index_name, df.index.astype(str).values)
    for col in df.columns:
        vals = df[col].values
        if vals.dtype.kind in "OUS":
            _write_string_array(g, str(col), pd.array(vals).astype(str))
        else:
            ds = g.create_dataset(str(col), data=np.asarray(vals))
            ds.attrs["encoding-type"] = "array"
            ds.attrs["encoding-version"] = "0.2.0"


def _x_compression() -> dict:
    """anndata's write_h5ad defaults to NO compression, and single-threaded
    gzip was the largest single cost of the prepare stage (~5 s of a 22 s
    run at gzip-1). Match the reference default; opt back in with
    CNMF_H5_COMPRESSION=gzip (level 1) or =lzf (fast, h5py-only filter)."""
    from .envknobs import env_str

    mode = env_str("CNMF_H5_COMPRESSION", "none").lower()
    if mode in ("", "none", "0", "off", "false"):
        return {}
    if mode == "lzf":
        return {"compression": "lzf"}
    if mode == "gzip":
        return {"compression": "gzip", "compression_opts": 1}
    raise ValueError(
        f"CNMF_H5_COMPRESSION={mode!r} not recognized; use 'none', 'gzip', "
        "or 'lzf'")


def _write_X(parent, X):
    comp = _x_compression()
    if sp.issparse(X):
        X = X.tocsr()
        g = parent.create_group("X")
        g.attrs["encoding-type"] = "csr_matrix"
        g.attrs["encoding-version"] = "0.1.0"
        g.attrs["shape"] = np.asarray(X.shape, dtype=np.int64)
        g.create_dataset("data", data=X.data, **comp)
        g.create_dataset("indices", data=X.indices, **comp)
        g.create_dataset("indptr", data=X.indptr, **comp)
    else:
        ds = parent.create_dataset("X", data=np.asarray(X), **comp)
        ds.attrs["encoding-type"] = "array"
        ds.attrs["encoding-version"] = "0.2.0"


def write_h5ad(filename: str, adata: AnnDataLite):
    import h5py

    from ..runtime.faults import maybe_tear

    # atomic (temp + os.replace): a worker killed mid-write must never
    # leave a truncated HDF5 that a later pipeline stage half-reads
    with atomic_artifact(filename) as tmp:
        with h5py.File(tmp, "w") as f:
            f.attrs["encoding-type"] = "anndata"
            f.attrs["encoding-version"] = "0.1.0"
            _write_X(f, adata.X)
            _write_dataframe(f, "obs", adata.obs)
            _write_dataframe(f, "var", adata.var)
            for aux in ("uns", "obsm", "varm", "obsp", "varp", "layers"):
                g = f.create_group(aux)
                g.attrs["encoding-type"] = "dict"
                g.attrs["encoding-version"] = "0.1.0"
            for key, val in getattr(adata, "obsm", {}).items():
                ds = f["obsm"].create_dataset(key, data=np.asarray(val))
                ds.attrs["encoding-type"] = "array"
                ds.attrs["encoding-version"] = "0.2.0"
    maybe_tear(filename)  # fault harness: no-op unless CNMF_TPU_FAULT_SPEC


def _decode(v):
    if isinstance(v, bytes):
        return v.decode("utf-8")
    return v


def _read_array_like(node):
    """Read a dataset or encoded group (categorical / nullable) as a 1-D array."""
    import h5py

    if isinstance(node, h5py.Dataset):
        vals = node[()]
        if vals.dtype.kind in "OS":
            vals = np.asarray([_decode(v) for v in vals], dtype=object)
        return vals
    enc = _decode(node.attrs.get("encoding-type", ""))
    if enc == "categorical":
        codes = node["codes"][()]
        cats = _read_array_like(node["categories"])
        out = pd.Categorical.from_codes(codes, categories=pd.Index(cats))
        return out
    if enc in ("nullable-integer", "nullable-boolean"):
        values = node["values"][()]
        mask = node["mask"][()]
        arr = values.astype(object)
        arr[mask.astype(bool)] = None
        return arr
    raise ValueError(f"unsupported h5ad column encoding: {enc!r}")


def _read_dataframe(g) -> pd.DataFrame:
    index_name = _decode(g.attrs.get("_index", "_index"))
    idx = pd.Index(_read_array_like(g[index_name]))
    col_order = [_decode(c) for c in g.attrs.get("column-order", [])]
    cols = {}
    for col in col_order:
        if col in g:
            cols[col] = _read_array_like(g[col])
    df = pd.DataFrame(cols, index=idx)
    if index_name != "_index":
        df.index.name = index_name
    return df


def _read_X(node):
    import h5py

    if isinstance(node, h5py.Dataset):
        return node[()]
    enc = _decode(node.attrs.get("encoding-type", ""))
    shape = tuple(node.attrs["shape"])
    data = node["data"][()]
    indices = node["indices"][()]
    indptr = node["indptr"][()]
    if enc == "csr_matrix":
        return sp.csr_matrix((data, indices, indptr), shape=shape)
    if enc == "csc_matrix":
        return sp.csc_matrix((data, indices, indptr), shape=shape).tocsr()
    raise ValueError(f"unsupported X encoding: {enc!r}")


def peek_h5ad_shape(filename: str) -> tuple[int, int]:
    """X's (n_obs, n_var) from the file metadata alone — no matrix read.
    Used to pre-compile shape-keyed consensus programs before the matrix is
    needed."""
    import h5py

    with h5py.File(filename, "r") as f:
        node = f["X"]
        if isinstance(node, h5py.Dataset):
            return tuple(int(s) for s in node.shape)
        return tuple(int(s) for s in node.attrs["shape"])


def peek_h5ad_var_names(filename: str):
    """The var (gene) index from the file metadata alone — no matrix
    read. The shard-store staleness sweep (ISSUE 10) compares it against
    a store manifest without materializing either matrix."""
    import h5py

    with h5py.File(filename, "r") as f:
        if "var" not in f:
            return None
        g = f["var"]
        index_name = _decode(g.attrs.get("_index", "_index"))
        return [str(_decode(v)) for v in _read_array_like(g[index_name])]


def read_h5ad(filename: str) -> AnnDataLite:
    import h5py

    with h5py.File(filename, "r") as f:
        X = _read_X(f["X"])
        obs = _read_dataframe(f["obs"]) if "obs" in f else None
        var = _read_dataframe(f["var"]) if "var" in f else None
        obsm = {}
        if "obsm" in f:
            for key, node in f["obsm"].items():
                if isinstance(node, h5py.Dataset):
                    obsm[key] = node[()]
    return AnnDataLite(X, obs, var, obsm)
