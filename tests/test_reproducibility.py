"""Golden-file reproducibility tier, mirroring the reference's design
(``/root/reference/tests/test_reproducibility.py``): run ``prepare()`` for
real and compare its deterministic artifacts; copy the golden merged-spectra
fixture into place INSTEAD of re-running the stochastic factorize ("Rather
than re-running factorization, we simply copy the combined files",
test_reproducibility.py:85-89); then run ``consensus()`` for real and
compare every downstream artifact at RMS < 1e-4. The seed ledger and the
persisted solver-kwargs YAML are under exact golden comparison — i.e. the
seed-derivation algorithm and solver configuration are pinned.

Goldens live in tests/golden/data/, regenerated only deliberately by
tests/golden/generate_goldens.py (no-egress stand-in for the reference's
GCS tarballs)."""

import os
import shutil

import numpy as np
import pytest
import yaml

from cnmf_torch_tpu import cNMF, load_df_from_npz

GOLDEN = os.path.join(os.path.dirname(__file__), "golden", "data")
RMS_TOL = 1e-4
KS = [4, 5]
CONSENSUS = [(4, "0_5"), (4, "2_0")]


def rms(a, b) -> float:
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    return float(np.sqrt(np.mean((a - b) ** 2)))


@pytest.fixture(scope="module")
def golden_run(tmp_path_factory):
    """prepare for real; inject golden merged spectra; consensus for real."""
    tmp = tmp_path_factory.mktemp("repro")
    obj = cNMF(output_dir=str(tmp), name="golden")
    obj.prepare(os.path.join(GOLDEN, "counts.df.npz"), components=KS,
                n_iter=6, seed=14, num_highvar_genes=120, batch_size=64,
                max_NMF_iter=200)
    for k in KS:
        shutil.copyfile(
            os.path.join(GOLDEN, f"golden.spectra.k_{k}.merged.df.npz"),
            obj.paths["merged_spectra"] % k)
    for k, dtr in CONSENSUS:
        dt = float(dtr.replace("_", "."))
        obj.consensus(k, density_threshold=dt, show_clustering=False,
                      build_ref=True)
    return obj


def _golden(name: str):
    return os.path.join(GOLDEN, name)


def test_seed_ledger_exact(golden_run):
    """Exact equality on [n_components, iter, nmf_seed] — pins the
    seed-derivation algorithm (reference test_reproducibility.py:160-165)."""
    got = load_df_from_npz(golden_run.paths["nmf_replicate_parameters"])
    want = load_df_from_npz(_golden("golden.nmf_params.df.npz"))
    for col in ["n_components", "iter", "nmf_seed"]:
        np.testing.assert_array_equal(got[col].values, want[col].values, col)


def test_solver_yaml_exact(golden_run):
    """Recursive dict equality on the persisted solver kwargs — the solver
    configuration itself is under golden test (reference
    test_reproducibility.py:14-39)."""
    got = yaml.safe_load(open(golden_run.paths["nmf_run_parameters"]))
    want = yaml.safe_load(open(_golden("golden.nmf_idvrun_params.yaml")))
    assert got == want


def test_hvg_list_exact(golden_run):
    got = open(golden_run.paths["nmf_genes_list"]).read()
    want = open(_golden("golden.overdispersed_genes.txt")).read()
    assert got == want


def test_tpm_stats_rms(golden_run):
    got = load_df_from_npz(golden_run.paths["tpm_stats"])
    want = load_df_from_npz(_golden("golden.tpm_stats.df.npz"))
    assert list(got.index) == list(want.index)
    assert rms(got.values, want.values) < RMS_TOL


@pytest.mark.parametrize("key,basename", [
    ("consensus_spectra", "golden.spectra.k_%d.dt_%s.consensus.df.npz"),
    ("consensus_usages", "golden.usages.k_%d.dt_%s.consensus.df.npz"),
    ("gene_spectra_score", "golden.gene_spectra_score.k_%d.dt_%s.df.npz"),
    ("gene_spectra_tpm", "golden.gene_spectra_tpm.k_%d.dt_%s.df.npz"),
    ("starcat_spectra", "golden.starcat_spectra.k_%d.dt_%s.df.npz"),
])
@pytest.mark.parametrize("k,dtr", CONSENSUS)
def test_consensus_artifacts_rms(golden_run, key, basename, k, dtr):
    got = load_df_from_npz(golden_run.paths[key] % (k, dtr))
    want = load_df_from_npz(_golden(basename % (k, dtr)))
    assert got.shape == want.shape
    assert list(got.index) == list(want.index)
    assert rms(got.values, want.values) < RMS_TOL, f"{key} k={k} dt={dtr}"


def test_k_selection_stats_rms(golden_run):
    stats = golden_run.k_selection_plot(close_fig=True)
    want = load_df_from_npz(_golden("golden.k_selection_stats.df.npz"))
    assert rms(stats[["k", "silhouette"]].values,
               want[["k", "silhouette"]].values) < RMS_TOL
    # prediction error is O(1e4); compare relatively
    np.testing.assert_allclose(stats["prediction_error"].values,
                               want["prediction_error"].values, rtol=1e-4)
