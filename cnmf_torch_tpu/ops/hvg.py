"""Over-dispersed (high-variance) gene selection by Fano factor.

Reimplementation of ``get_highvar_genes_sparse`` / ``get_highvar_genes``
(``/root/reference/src/cnmf/cnmf.py:133-238``): genes are scored by the ratio
of their Fano factor (var/mean) to an expected-Fano line ``A^2 * mean + B^2``,
where ``A`` comes from the top-20-mean genes' coefficient of variation and
``B`` from the winsorized (10-90th percentile box) median Fano. Selection is
either top-``numgenes`` by ``fano_ratio`` or thresholded at
``T = 1 + std(fano in box)`` with a ``minimal_mean`` floor.

The O(cells x genes) moment pass runs through
:func:`cnmf_torch_tpu.ops.stats.column_moments_staged` /
:func:`~cnmf_torch_tpu.ops.stats.column_mean_var`; the scoring itself is
O(genes) quantile/median/ranking work and runs on HOST in exact float64 —
a jitted version spent ~70 s compiling TPU sorting networks for a
5,000-element computation that numpy finishes in microseconds, and host f64
reproduces the reference's pandas ranking exactly (no fp32 ties at the
selection cutoff).
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from .stats import column_mean_var

__all__ = ["highvar_genes"]


def _fano_scores(mean, var, numgenes, has_threshold, expected_fano_threshold,
                 minimal_mean):
    with np.errstate(divide="ignore", invalid="ignore"):
        fano = var / mean

        # A: min CV among the 20 highest-mean genes (cnmf.py:144-145);
        # stable sort = pandas sort_values tie order
        top20 = np.argsort(-mean, kind="stable")[: min(20, mean.shape[0])]
        A = float(np.min(np.sqrt(var[top20]) / mean[top20]))

        # winsor box: 10th-90th pctile in both mean and fano
        # (cnmf.py:147-152); pandas .quantile skips NaN -> nanquantile. NaN
        # fano (zero-mean genes) never enters the box: comparisons are False.
        w_mean_low, w_mean_high = np.nanquantile(mean, [0.10, 0.90])
        w_fano_low, w_fano_high = np.nanquantile(fano, [0.10, 0.90])
        box = ((fano > w_fano_low) & (fano < w_fano_high)
               & (mean > w_mean_low) & (mean < w_mean_high))
        boxed = fano[box]
        B = float(np.sqrt(np.median(boxed)))

        expected_fano = (A ** 2) * mean + (B ** 2)
        fano_ratio = fano / expected_fano

    if numgenes is not None:
        # top-N selection; NaN ratios (zero-mean genes) sort last
        score = np.where(np.isnan(fano_ratio), -np.inf, fano_ratio)
        idx = np.argsort(-score, kind="stable")[:numgenes]
        high_var = np.zeros(mean.shape, dtype=bool)
        high_var[idx] = True
        T = np.nan
    else:
        if has_threshold:
            T = float(expected_fano_threshold)
        else:
            # pandas .std() on the boxed fano = sample std, ddof=1
            # (cnmf.py:167)
            T = float(1.0 + boxed.std(ddof=1))
        with np.errstate(invalid="ignore"):
            high_var = (fano_ratio > T) & (mean > minimal_mean)

    return fano, expected_fano, fano_ratio, high_var, A, B, T


def highvar_genes(X, expected_fano_threshold=None, minimal_mean: float = 0.5,
                  numgenes: int | None = None, precomputed_moments=None):
    """Score genes for over-dispersion; X is cells x genes (sparse or dense).

    Returns ``(gene_stats, params)`` with the same schema as the reference:
    ``gene_stats`` has columns [mean, var, fano, expected_fano, high_var,
    fano_ratio]; ``params`` is ``{'A','B','T','minimal_mean'}``.

    The reference's sparse path uses population variance (ddof=0 via
    StandardScaler, cnmf.py:138) and its dense path likewise (ddof=0,
    cnmf.py:192); both map to one kernel here.

    ``precomputed_moments``: optional ``(mean, var)`` population moments of
    X — prepare() already computes them for the tpm_stats artifact
    (``cnmf.py:570-580``) from one fused moment pass
    (:func:`~cnmf_torch_tpu.ops.stats.column_moments_staged`); passing them
    here skips a redundant O(cells x genes) pass.
    """
    if precomputed_moments is not None:
        mean, var = precomputed_moments
    else:
        mean, var = column_mean_var(X, ddof=0)
    mean = np.asarray(mean, dtype=np.float64)
    var = np.asarray(var, dtype=np.float64)
    # mirrors the reference's truthiness test `if not expected_fano_threshold`
    # (cnmf.py:166): None or 0.0 both fall back to the computed T
    has_threshold = bool(expected_fano_threshold)
    fano, expected_fano, fano_ratio, high_var, A, B, T = _fano_scores(
        mean, var,
        None if numgenes is None else min(int(numgenes), X.shape[1]),
        has_threshold,
        expected_fano_threshold if has_threshold else 0.0,
        minimal_mean,
    )
    gene_stats = pd.DataFrame({
        "mean": mean,
        "var": var,
        "fano": fano,
        "expected_fano": expected_fano,
        "high_var": high_var,
        "fano_ratio": fano_ratio,
    })
    params = {
        "A": float(A), "B": float(B),
        "T": None if numgenes is not None else float(T),
        "minimal_mean": minimal_mean,
    }
    return gene_stats, params
