"""End-to-end pipeline launcher — the reference ``Extras/run_parallel.py``
equivalent (``/root/reference/Extras/run_parallel.py:1-70``: prepare -> GNU
parallel factorize workers -> combine -> k_selection_plot -> clean).

Two engines replace GNU parallel:

  * ``subprocess`` — N independent OS worker processes, round-robin sharded
    by ``--worker-index`` over the replicate ledger, exactly the reference's
    model (files as the dataplane). Right for a fleet of single-chip hosts
    with a shared filesystem and for CPU dev boxes. Self-healing (ISSUE 5):
    a worker that dies (or exceeds ``CNMF_TPU_WORKER_TIMEOUT`` seconds and
    is killed) is respawned onto its own unfinished ledger shard with
    ``--skip-completed-runs`` — resume rides the eager, atomic per-replicate
    artifacts AND, on the rowsharded path, the newest valid mid-run pass
    checkpoint (``runtime/checkpoint.py``), so a worker killed 40 passes
    into a multi-hour replicate restarts mid-run, not from scratch — after
    an exponential backoff with deterministic per-worker jitter
    (:func:`respawn_delay`), up to ``CNMF_TPU_WORKER_RESPAWNS`` times
    (default 1). Elastic (ISSUE 8, ``CNMF_TPU_ELASTIC``): once any worker
    has finished cleanly, dead shards are ADOPTED by the idle fleet
    immediately (work-stealing, no backoff) and get one extra adoption
    wave past the respawn budget; a worker whose run exceeds the longest
    clean finisher's wall time by ``CNMF_TPU_STRAGGLER_S`` seconds with
    a stale heartbeat is killed and contained the same way. Only when
    every recovery lever is
    exhausted does the run fall back to the reference's dead-worker
    tolerance: combine with ``skip_missing_files=True``.
  * ``multihost`` — ONE single-controller JAX program spanning N processes
    stitched by ``jax.distributed`` (``parallel/multihost.py``); factorize
    runs over the 2-D (replicates x cells) mesh, with the cells-psum on ICI
    and the replicate axis across hosts. On a real TPU pod you normally
    launch that yourself (same command on every host); this engine spawns
    the N processes locally — with ``--devices-per-host`` virtual CPU
    devices each — which is how the multi-host path is CI-tested without a
    pod.

Python API: :func:`run_pipeline`. CLI: ``cnmf-tpu run_parallel ...``.
"""

from __future__ import annotations

import glob
import os
import socket
import subprocess
import sys
import warnings

__all__ = ["run_pipeline", "respawn_delay"]


def respawn_delay(backoff_s: float, attempt: int, worker_i: int) -> float:
    """Respawn backoff for a dead worker: exponential base
    (``backoff_s * 2^(attempt-1)``) times a deterministic per-worker
    jitter factor in [1, 1.5). The jitter derives from the worker index
    alone (Knuth multiplicative hash — no RNG, so resume/replay timing is
    reproducible): when a whole fleet dies at once (node preemption,
    shared-filesystem blip), the respawns fan out across half a backoff
    period instead of restarting in lockstep and re-stampeding whatever
    killed them."""
    base = float(backoff_s) * (2 ** (max(int(attempt), 1) - 1))
    jitter = ((int(worker_i) * 2654435761) & 0xFFFFFFFF) % 1024 / 2048.0
    return base * (1.0 + jitter)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_cmd(output_dir: str, name: str, extra: list[str]) -> list[str]:
    return [sys.executable, "-m", "cnmf_torch_tpu", "factorize",
            "--output-dir", output_dir, "--name", name] + extra


def _run_subprocess_workers(
        output_dir: str, name: str, total_workers: int,
        factorize_flags: list[str], base_env: dict,
        poll_s: float = 0.05, events=None) -> tuple[set[int], set[int]]:
    """Run the subprocess-engine worker fleet with self-healing: per-worker
    wall timeouts (``CNMF_TPU_WORKER_TIMEOUT`` seconds; 0/unset = none)
    and bounded exponential-backoff respawn of dead workers
    (``CNMF_TPU_WORKER_RESPAWNS`` attempts, delays
    ``CNMF_TPU_WORKER_BACKOFF_S * 2^(attempt-1)``). A respawned worker
    resumes its OWN round-robin ledger shard via ``--skip-completed-runs``
    — factorize probes AND validates the eager per-replicate artifacts, so
    a SIGKILL'd predecessor's torn files are rerun, not trusted.

    Elastic work-stealing (ISSUE 8, on unless ``CNMF_TPU_ELASTIC=0``):
    once any worker has finished cleanly — proof the environment solves
    and there is idle capacity — a dead worker's unfinished ``(k, iter)``
    cells are ADOPTED by the fleet instead of waiting out the fixed-shard
    backoff ladder: the adoption spawns immediately onto the orphan shard
    with ``--skip-completed-runs`` (the probe skips the dead worker's
    completed cells), and a shard whose respawn budget is exhausted gets
    one further adoption wave before combine degrades around it — a
    budget exhausted BEFORE any worker finished defers that wave until
    the first clean finisher proves the environment (an early-crashing
    shard does not forfeit its adoption just by crashing first). The
    adopter runs under the orphan's ``--worker-index``, so its resilience
    ledger (``*.resilience.w<N>.json``), provenance record, and
    min-healthy-frac floor accounting stay exactly where the dead
    worker's would have been — quarantine records carry over instead of
    double-counting or vanishing.

    Straggler containment (``CNMF_TPU_STRAGGLER_S``, part of the elastic
    layer — inert under ``CNMF_TPU_ELASTIC=0``, and REQUIRES liveness,
    ``CNMF_TPU_HEARTBEAT_S``): the longest clean finisher's wall time is
    the fleet's observed shard runtime; a worker whose OWN elapsed (from
    its own spawn, so adoptions doing a full shard's work get a full
    allowance) exceeds that baseline by ``CNMF_TPU_STRAGGLER_S`` seconds
    AND whose heartbeat is stale (older than ``max(grace, 3 x heartbeat
    interval)`` — the barrier diagnosis's presumed-dead multiple) is
    killed and contained through the same adoption path, before one slow
    shard wedges the sweep. A worker stamping liveness on schedule is
    never convicted: conviction needs both "past the fleet's wall" and
    "no evidence of progress" — resumed runs have wildly unequal shards,
    and a near-instant already-complete shard must not convict the one
    doing real work. At most ONE straggler conviction per shard: a
    second conviction at the same point would mean the deadline is wrong
    (e.g. the shard's remaining work is one long jitted dispatch that
    cannot stamp liveness mid-flight), so the containment respawn runs
    to completion untouched — the straggler path alone can never
    permanently fail a shard. Both containment kinds land in telemetry
    as ``fault`` events (``worker_steal`` / ``straggler``) when
    ``events`` is given.

    Returns ``(failed, unhealthy)``: worker indices that stayed dead
    after the recovery budget, and workers that exited with
    ``resilience.UNHEALTHY_EXIT_CODE`` (below the min-healthy-frac floor
    — a deterministic policy failure that is neither respawned nor
    degraded around; the caller aborts the pipeline)."""
    import time

    from .runtime import elastic
    from .runtime.resilience import UNHEALTHY_EXIT_CODE

    from .utils.envknobs import env_float, env_int

    respawn_limit = env_int("CNMF_TPU_WORKER_RESPAWNS", 1, lo=0)
    timeout_s = env_float("CNMF_TPU_WORKER_TIMEOUT", 0.0, lo=0.0)
    backoff_s = env_float("CNMF_TPU_WORKER_BACKOFF_S", 0.5, lo=0.0)
    steal_on = elastic.elastic_enabled()
    straggler_s = elastic.straggler_deadline_s()
    hb_interval = elastic.heartbeat_s()
    # straggler conviction is EVIDENCE-based: it needs liveness
    # (CNMF_TPU_HEARTBEAT_S) so "slow but progressing" is distinguishable
    # from "wedged" — a wall clock alone would convict healthy workers on
    # resumed runs, whose shards are wildly unequal (a near-instant
    # already-complete shard must not set the bar for one doing real
    # work). The stale window is the larger of the grace and 3x the
    # heartbeat interval (the same presumed-dead multiple the barrier
    # diagnosis uses), so a worker beating on schedule is never convicted.
    straggler_on = steal_on and straggler_s > 0 and hb_interval > 0
    stale_window = max(straggler_s, 3.0 * hb_interval)
    if steal_on and straggler_s > 0 and hb_interval <= 0:
        warnings.warn(
            "CNMF_TPU_STRAGGLER_S is set but CNMF_TPU_HEARTBEAT_S is off: "
            "straggler containment needs liveness evidence to avoid "
            "killing slow-but-healthy workers (resumed runs have wildly "
            "unequal shards) — the deadline is disabled. Set "
            "CNMF_TPU_HEARTBEAT_S to arm it.", RuntimeWarning)

    def spawn(i: int, resume: bool):
        flags = ["--worker-index", str(i),
                 "--total-workers", str(total_workers)]
        if resume and "--skip-completed-runs" not in factorize_flags:
            flags.append("--skip-completed-runs")
        return subprocess.Popen(
            _worker_cmd(output_dir, name, flags + factorize_flags),
            env=base_env)

    def _emit(kind: str, **context):
        if events is not None:
            events.emit("fault", kind=kind, context=context)

    def _read_heartbeat(i: int):
        return elastic.Heartbeat.read(os.path.join(
            output_dir, name, "cnmf_tmp", f"{name}.heartbeat.{i}.json"))

    def _last_heartbeat(i: int) -> str:
        """The worker's last liveness stamp, for diagnosis messages —
        empty when heartbeats are off or never landed. Rendered by the
        shared :meth:`Heartbeat.describe` formatter so launcher and
        barrier diagnoses read the same way."""
        rec = _read_heartbeat(i)
        if not rec:
            return ""
        import time as _time

        age = None
        try:
            age = round(max(0.0, _time.time() - float(rec["ts"])), 1)
        except (KeyError, TypeError, ValueError):
            pass
        return "; " + elastic.Heartbeat.describe(
            [{"index": i, "age_s": age, "phase": rec.get("phase"),
              "cursor": rec.get("cursor")}])

    def _heartbeat_fresh(i: int, within_s: float) -> bool:
        """True when the worker stamped liveness within ``within_s`` —
        evidence of real progress that vetoes a wall-clock straggler
        conviction."""
        rec = _read_heartbeat(i)
        if not rec:
            return False
        import time as _time

        try:
            return _time.time() - float(rec["ts"]) <= within_s
        except (KeyError, TypeError, ValueError):
            return False

    now = time.monotonic
    procs = {i: spawn(i, False) for i in range(total_workers)}
    started = {i: now() for i in procs}
    deadline = {i: (now() + timeout_s if timeout_s > 0 else None)
                for i in procs}
    attempts = {i: 0 for i in procs}
    adoptions = {i: 0 for i in procs}
    respawn_at: dict[int, float] = {}
    failed: set[int] = set()
    unhealthy: set[int] = set()
    finished: set[int] = set()
    # shards whose respawn budget died BEFORE any worker finished: their
    # adoption wave is deferred until a clean finisher proves the
    # environment (an early-crashing shard must not forfeit the wave
    # just because it crashed first)
    deferred: set[int] = set()
    # at most ONE straggler conviction per shard: a second conviction at
    # the same point means the deadline is wrong (e.g. the shard's work
    # is one long jitted dispatch that cannot stamp liveness mid-flight),
    # not the shard — the adoption is then left to run to completion, so
    # the straggler path alone can never permanently fail a shard
    straggled: set[int] = set()
    # the longest clean finisher's wall time: the fleet's observed shard
    # runtime, baseline for the straggler deadline
    baseline_s: float | None = None

    def _recover(i: int, rc) -> None:
        """Schedule recovery for dead shard ``i``: fixed-shard respawn
        with backoff while the budget lasts (immediate, labeled adoption
        when the idle fleet can steal), one bonus adoption wave after
        the budget, then the reference's dead-worker tolerance."""
        can_steal = steal_on and bool(finished)
        if attempts[i] < respawn_limit:
            attempts[i] += 1
            if can_steal:
                warnings.warn(
                    "factorize worker %d died (rc=%s); its unfinished "
                    "cells are adopted by the idle fleet now (work-"
                    "stealing via --skip-completed-runs, attempt %d/%d)"
                    % (i, rc, attempts[i], respawn_limit),
                    RuntimeWarning)
                _emit("worker_steal", shard=i, attempt=attempts[i],
                      reason="dead_worker")
                respawn_at[i] = now()
            else:
                delay = respawn_delay(backoff_s, attempts[i], i)
                warnings.warn(
                    "factorize worker %d died (rc=%s); respawning onto its "
                    "unfinished ledger shard in %.1fs (attempt %d/%d)"
                    % (i, rc, delay, attempts[i], respawn_limit),
                    RuntimeWarning)
                respawn_at[i] = now() + delay
        elif can_steal and adoptions[i] < 1:
            # respawn budget burned — one adoption wave by the proven-
            # healthy fleet before giving the shard up: the budget guards
            # against a sick environment, and a clean finisher is the
            # evidence the environment is fine
            adoptions[i] += 1
            warnings.warn(
                "factorize worker %d exhausted its respawn budget; one "
                "adoption wave steals its unfinished cells before combine "
                "degrades around them" % i, RuntimeWarning)
            _emit("worker_steal", shard=i,
                  attempt=respawn_limit + adoptions[i],
                  reason="respawn_budget_exhausted")
            respawn_at[i] = now()
        elif steal_on and not finished and adoptions[i] < 1:
            # budget exhausted before ANY worker finished: park the
            # shard — its adoption wave fires when the first clean
            # finisher proves the environment (below). If nothing ever
            # finishes, the run-exit sweep converts deferred to failed.
            deferred.add(i)
            warnings.warn(
                "factorize worker %d exhausted its respawn budget before "
                "any worker finished; its adoption wave is deferred "
                "until the fleet proves the environment" % i,
                RuntimeWarning)
        else:
            failed.add(i)
            warnings.warn(
                "factorize worker %d exited with rc=%s; its replicates "
                "will be skipped at combine (the reference's dead-worker "
                "tolerance, cnmf.py:904-909)" % (i, rc),
                RuntimeWarning)

    while procs or respawn_at:
        for i in [j for j, t in respawn_at.items() if now() >= t]:
            del respawn_at[i]
            procs[i] = spawn(i, True)
            started[i] = now()
            deadline[i] = now() + timeout_s if timeout_s > 0 else None
        for i in list(procs):
            p = procs[i]
            rc = p.poll()
            if rc is None:
                if deadline[i] is not None and now() > deadline[i]:
                    warnings.warn(
                        "factorize worker %d exceeded CNMF_TPU_WORKER_"
                        "TIMEOUT=%gs; killing it" % (i, timeout_s),
                        RuntimeWarning)
                    p.kill()
                    p.wait()
                    rc = p.returncode
                elif (straggler_on and baseline_s is not None
                        and i not in straggled
                        # never convict without a recovery lever left:
                        # killing a still-working process that nothing
                        # can adopt would be strictly worse than letting
                        # it finish
                        and (attempts[i] < respawn_limit
                             or adoptions[i] < 1)
                        and now() - started[i] > baseline_s + straggler_s
                        and not _heartbeat_fresh(i, stale_window)):
                    # straggler deadline: this run has exceeded the
                    # fleet's observed shard runtime (the longest clean
                    # finisher's wall) by the grace, with no fresh
                    # heartbeat vetoing the conviction — contain it
                    # (kill + adoption resumes its completed cells)
                    # before it wedges the sweep. Measured from the
                    # process's OWN spawn, so an adoption redoing a full
                    # shard gets a full allowance, not an instant kill.
                    warnings.warn(
                        "factorize worker %d is a straggler (%.0fs "
                        "elapsed vs the fleet's %.0fs shard wall + "
                        "CNMF_TPU_STRAGGLER_S=%gs grace)%s; killing + "
                        "adopting its shard"
                        % (i, now() - started[i], baseline_s, straggler_s,
                           _last_heartbeat(i)),
                        RuntimeWarning)
                    _emit("straggler", worker=i, deadline_s=straggler_s,
                          elapsed_s=round(now() - started[i], 1),
                          baseline_s=round(baseline_s, 1))
                    straggled.add(i)
                    p.kill()
                    p.wait()
                    rc = p.returncode
                else:
                    continue
            del procs[i]
            if rc == 0:
                finished.add(i)
                # the LONGEST clean wall so far: heterogeneous shards
                # (and resumed runs' near-instant complete shards) must
                # not convict a peer doing a full shard's work
                baseline_s = max(baseline_s or 0.0, now() - started[i])
                # the environment just proved itself: fire the deferred
                # adoption waves of shards that crashed out early
                for j in sorted(deferred):
                    adoptions[j] += 1
                    warnings.warn(
                        "factorize worker %d's deferred adoption wave "
                        "fires now (worker %d finished cleanly)"
                        % (j, i), RuntimeWarning)
                    _emit("worker_steal", shard=j,
                          attempt=attempts[j] + adoptions[j],
                          reason="deferred_until_fleet_proved")
                    respawn_at[j] = now()
                deferred.clear()
                continue
            if rc == UNHEALTHY_EXIT_CODE:
                # below the min-healthy-frac floor: deterministic — a
                # respawn reruns the same derived seeds and fails the
                # same way, so don't burn the budget
                unhealthy.add(i)
                continue
            _recover(i, rc)
        if procs or respawn_at:
            time.sleep(poll_s)
    if deferred:
        # nothing ever finished cleanly — the deferred shards' adoption
        # never had a healthy fleet to run on; they are failed like the
        # pre-elastic budget-exhausted case
        for i in sorted(deferred):
            failed.add(i)
            warnings.warn(
                "factorize worker %d's deferred adoption never ran (no "
                "worker finished cleanly); its replicates will be "
                "skipped at combine" % i, RuntimeWarning)
    return failed, unhealthy


def run_pipeline(counts: str, output_dir: str, name: str,
                 components, n_iter: int = 100, total_workers: int = 1,
                 seed: int | None = None, numgenes: int = 2000,
                 genes_file: str | None = None, tpm: str | None = None,
                 beta_loss: str = "frobenius", init: str = "random",
                 max_nmf_iter: int = 1000, batch_size: int = 5000,
                 engine: str = "subprocess",
                 devices_per_host: int | None = None,
                 clean: bool = False, k_selection: bool = True,
                 env_extra: dict | None = None,
                 factorize_flags: list[str] | None = None) -> None:
    """prepare -> parallel factorize -> combine -> k_selection_plot.

    ``engine='subprocess'``: ``total_workers`` OS processes shard the ledger
    (the reference's GNU-parallel model). ``engine='multihost'``:
    ``total_workers`` JAX processes form one distributed program over a 2-D
    mesh; ``devices_per_host`` forces that many virtual CPU devices per
    process (pod simulation — omit on real multi-chip hosts).

    ``factorize_flags``: extra CLI flags forwarded verbatim to every
    factorize worker (e.g. ``["--mesh-2d"]``, ``["--rowshard"]``,
    ``["--sequential"]``) — how the run_parallel subcommand's
    factorize-mode options reach the workers.
    """
    factorize_flags = list(factorize_flags or [])
    # the CLI's parser default is -1 ("all"); range(-1) would spawn zero
    # workers and the run would only fail much later at combine
    total_workers = max(int(total_workers), 1)
    if engine not in ("subprocess", "multihost"):
        raise ValueError(f"unknown engine {engine!r}")
    if engine == "multihost" and devices_per_host is None:
        # this process is about to initialize a JAX backend for prepare();
        # N spawned children sharing the parent's real TPU runtime would
        # contend for the chips and hang or crash. The local-spawn engine
        # is only safe when each child gets its own virtual CPU devices; on
        # a real pod, launch the same command on every host instead
        # (docs/Stepwise_Guide.md). Checked BEFORE prepare so the
        # misconfiguration costs seconds, not an atlas-scale prepare pass.
        import jax

        if jax.default_backend() not in ("cpu",):
            raise RuntimeError(
                "engine='multihost' without devices_per_host spawns "
                "local JAX processes that would contend with this "
                "process's %r backend. Pass devices_per_host=N for a "
                "CPU-simulated pod, or launch one process per host "
                "yourself with CNMF_PROCESS_ID/--distributed (see "
                "docs/Stepwise_Guide.md)." % jax.default_backend())
    from .models.cnmf import cNMF

    obj = cNMF(output_dir=output_dir, name=name)
    obj.prepare(counts, components=components, n_iter=n_iter, seed=seed,
                num_highvar_genes=numgenes, genes_file=genes_file,
                tpm_fn=tpm, beta_loss=beta_loss, init=init,
                max_NMF_iter=max_nmf_iter, batch_size=batch_size,
                total_workers=max(total_workers, 1))

    base_env = dict(os.environ)
    # workers must import this package regardless of their cwd (source
    # checkouts aren't necessarily pip-installed)
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_env["PYTHONPATH"] = os.pathsep.join(
        [pkg_root] + ([base_env["PYTHONPATH"]]
                      if base_env.get("PYTHONPATH") else []))
    if env_extra:
        base_env.update({k: str(v) for k, v in env_extra.items()})

    # distributed tracing (obs/tracing.py): when sampling is on, the
    # launcher owns the run's root trace and plants it in the worker
    # environment — every worker's process_context() then parents its
    # spans on this one and `cnmf-tpu trace` renders parent -> workers
    # as one waterfall. None (the common case) costs nothing.
    from .obs import tracing as obs_tracing

    run_trace = obs_tracing.new_trace()
    if run_trace is not None:
        base_env[obs_tracing.TRACE_CTX_ENV] = obs_tracing.env_value(
            run_trace)

    any_failed = False
    if engine == "subprocess":
        # launcher-side telemetry: work-stealing adoptions and straggler
        # containment append to the SAME per-run events file the workers
        # write (no-op unless CNMF_TPU_TELEMETRY) — `cnmf-tpu report`
        # then renders one mesh-elasticity audit trail for the run
        from .utils.telemetry import EventLog

        events = EventLog(os.path.join(
            output_dir, name, "cnmf_tmp", f"{name}.events.jsonl"))
        # the root span covers the whole worker phase; worker-side spans
        # (factorize.worker etc.) land in the same events file and parent
        # on run_trace's span id via CNMF_TPU_TRACE_CTX
        with obs_tracing.span(events, run_trace, "launcher.run",
                              workers=total_workers):
            failed, unhealthy = _run_subprocess_workers(
                output_dir, name, total_workers, factorize_flags, base_env,
                events=events)
        if unhealthy:
            # the min-healthy-frac floor is a hard guarantee end-to-end:
            # degrading around it with skip-missing combine would produce
            # exactly the under-powered consensus it exists to prevent
            raise RuntimeError(
                "factorize worker(s) %s reported too few healthy "
                "replicates (below CNMF_TPU_MIN_HEALTHY_FRAC; see their "
                "output above) — aborting before combine/consensus"
                % sorted(unhealthy))
        any_failed = bool(failed)
        if len(failed) == total_workers:
            # nothing survived — combine/k_selection would only crash on
            # missing files with a misleading traceback
            raise RuntimeError(
                f"all {total_workers} factorize workers failed (respawn "
                "budget exhausted); see their output above")
    elif engine == "multihost":
        port = _free_port()
        procs = []
        for pid in range(total_workers):
            env = dict(base_env,
                       CNMF_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                       CNMF_NUM_PROCESSES=str(total_workers),
                       CNMF_PROCESS_ID=str(pid))
            if devices_per_host:
                env["CNMF_SIM_CPU_DEVICES"] = str(devices_per_host)
            extra = ["--mesh-2d", "--distributed"] + [
                f for f in factorize_flags if f != "--mesh-2d"]
            cmd = _worker_cmd(output_dir, name, extra)
            procs.append((pid, subprocess.Popen(cmd, env=env)))
        rcs = [(pid, p.wait()) for pid, p in procs]
        bad = [(pid, rc) for pid, rc in rcs if rc]
        if bad:
            # a single-controller program has no partial completion: one
            # dead process stalls the collective, unlike the subprocess
            # engine's independent workers
            raise RuntimeError(
                f"multihost factorize failed on processes {bad}")

    obj.combine(skip_missing_files=any_failed)
    if k_selection:
        obj.k_selection_plot(close_fig=True)

    if clean:
        _clean_run_dir(os.path.join(output_dir, name))


def _clean_run_dir(run_dir: str):
    """The reference's `rm .../cnmf_tmp/*.iter_*.df.npz`
    (run_parallel.py:64): per-replicate spectra are redundant once
    merged_spectra exists. Also sweep pid-suffixed atomic-write temp
    files orphaned by killed workers (utils/anndata_lite
    .atomic_artifact) — no reader ever trusts them, but they accumulate
    across preemptions; all workers have exited by here, so none are
    live. The shard store itself SURVIVES (a prepare artifact, reusable
    on resume — and under CNMF_TPU_OOC=1 the only copy of the matrix);
    only its temp orphans are swept."""
    for pattern in (os.path.join("cnmf_tmp", "*.iter_*.df.npz"),
                    # pass checkpoints are normally discarded when
                    # their replicate's artifact lands; a worker that
                    # exhausted its respawn budget can leave one behind
                    os.path.join("cnmf_tmp", "*.ckpt.k_*.npz"),
                    # liveness stamps (CNMF_TPU_HEARTBEAT_S) are
                    # meaningful only while their writer is alive
                    os.path.join("cnmf_tmp", "*.heartbeat.*.json"),
                    # atomic-write temp orphans land wherever their
                    # artifact lives: intermediates in cnmf_tmp/, the
                    # txt/stats finals in the run dir itself, shard-store
                    # slabs inside the store directory (ISSUE 10)
                    os.path.join("cnmf_tmp", "*.tmp-*"),
                    os.path.join("cnmf_tmp", "*.norm_counts.store",
                                 "*.tmp-*"),
                    # the remote backend's read-through cache (ISSUE 15)
                    # is a re-fetchable optimization, not an artifact:
                    # sweep entries, digest sidecars, and temp orphans
                    os.path.join("cnmf_tmp", "*.norm_counts.store.cache",
                                 "*"),
                    "*.tmp-*"):
        for f in glob.glob(os.path.join(run_dir, pattern)):
            os.remove(f)
