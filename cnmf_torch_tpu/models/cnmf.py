"""The consensus-NMF pipeline: prepare -> factorize -> combine -> consensus.

API- and artifact-compatible reimplementation of the reference ``cNMF`` class
(``/root/reference/src/cnmf/cnmf.py:390-1384``) on the JAX/XLA compute stack:

  * the five pipeline stages, the 25-key path registry, the replicate seed
    ledger, and every on-disk artifact keep the reference's exact contract
    (filenames, DataFrame-npz layout, seed derivation) so outputs are
    drop-in interchangeable and golden-file testable;
  * execution is TPU-first: ``factorize`` runs each K's replicates as ONE
    batched, mesh-sharded XLA program (``cnmf_torch_tpu.parallel``) instead
    of the reference's one-process-per-replicate model, and every consensus
    kernel (distances, KNN density, k-means, silhouette, MU refits, batched
    OLS) is a jit-compiled op from ``cnmf_torch_tpu.ops``.

The filesystem remains the durable checkpoint layer (every stage's outputs
are its checkpoint, SURVEY.md §1.1/§5.4); collectives replace it only as the
live communication path between replicates.
"""

from __future__ import annotations

import datetime
import os
import time
import uuid
import warnings

import numpy as np
import pandas as pd
import scipy.sparse as sp
import yaml

from ..ops import (
    highvar_genes,
    kmeans,
    local_density as knn_local_density,
    normalize_total,
    ols_all_cols,
    scale_columns,
    silhouette_score,
)
from ..ops.nmf import (beta_loss_to_float, fit_h, resolve_online_schedule,
                       run_nmf)
from ..ops.sketch import project_rows, resolve_consensus_sketch
from ..parallel import replicate_sweep, worker_filter
from ..utils.anndata_lite import (AnnDataLite, atomic_artifact, read_h5ad,
                                  write_h5ad)
from ..utils.envknobs import env_flag, env_int
from ..utils.io import (
    load_counts,
    load_df_from_npz,
    save_df_to_npz,
    save_df_to_text,
)
from ..utils.paths import build_paths
from ..utils.profiling import StageTimer, trace
from ..utils.telemetry import EventLog

__all__ = ["cNMF"]

# Fallback when a hand-edited solver YAML omits online_chunk_max_iter — the
# reference CLI's --max-nmf-iter default (cnmf.py:1424); prepare() always
# persists the key. NOT the same knob as the usage-refit's inner cap, whose
# reference default is 200 (fit_H_online, cnmf.py:264) and which this
# pipeline always passes explicitly from the YAML.
_DEFAULT_CHUNK_MAX_ITER = 1000


def _delete_staged(x):
    """Free a staged device array (dense ``jax.Array`` or an EllMatrix's
    four leaves) ahead of a degraded re-mesh: the survivors must not hold
    the doomed topology's shards while the replacement uploads — at atlas
    scale that transient doubling is an OOM. Best-effort: a backend that
    cannot delete just garbage-collects later."""
    leaves = ((x.vals, x.cols, x.rows_t, x.perm_t) if hasattr(x, "vals")
              else (x,))
    for leaf in leaves:
        try:
            leaf.delete()
        except Exception:
            pass


def compute_tpm(input_counts: AnnDataLite, totals=None) -> AnnDataLite:
    """Per-cell scaling to 1e6 total counts (``cnmf.py:241-247``);
    ``totals`` threads precomputed row sums through (one matrix pass)."""
    return normalize_total(input_counts, target_sum=1e6, totals=totals)


def _timed(stage_name: str):
    """Record a pipeline stage in the run's timing ledger, (when
    CNMF_TPU_PROFILE_DIR is set) an XLA profiler trace, and (when
    CNMF_TPU_TELEMETRY is set) a device-memory watermark at the stage
    boundary."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            try:
                with self._timer.stage(stage_name), trace(stage_name):
                    return fn(self, *args, **kwargs)
            finally:
                self._events.emit_memory(stage_name)
        return wrapper
    return deco


class cNMF:
    """Consensus NMF pipeline over an output-directory artifact store.

    Same constructor contract as the reference (``cnmf.py:393-414``):
    unnamed runs get ``YYYY_MM_DD_<6-hex>`` names; all artifacts live under
    ``output_dir/name/`` with intermediates in ``cnmf_tmp/``.
    """

    def __init__(self, output_dir: str = ".", name: str | None = None,
                 rowshard_threshold: int = 200_000):
        self.output_dir = output_dir
        if name is None:
            now = datetime.datetime.now()
            name = "%s_%s" % (now.strftime("%Y_%m_%d"), uuid.uuid4().hex[:6])
        self.name = name
        # cell count above which factorize AND the consensus refits switch
        # to the row-sharded/streaming kernels instead of densifying X
        # (BASELINE config 5; no reference counterpart — the reference
        # densifies at every solver boundary, cnmf.py:817-818, 329-330)
        self.rowshard_threshold = int(rowshard_threshold)
        self.paths = build_paths(output_dir, name)
        # structured run telemetry (ISSUE 4): JSONL event stream next to
        # the timings TSV — manifest, dispatch decisions, stage walls,
        # replicate convergence, stream stats, memory watermarks. Inert
        # (no file, no ops in the jitted solvers) unless CNMF_TPU_TELEMETRY
        # is set; the enabled check runs per-emit so env toggles work on a
        # live object.
        self._events = EventLog(os.path.join(
            output_dir, name, "cnmf_tmp", name + ".events.jsonl"),
            manifest_extra={"run_name": name})
        # per-stage wall-clock ledger + optional XLA traces (SURVEY.md §5.1:
        # the reference has no tracing; this fills that gap); rows mirror
        # into the event stream as `stage` events
        self._timer = StageTimer(os.path.join(
            output_dir, name, "cnmf_tmp", name + ".timings.tsv"),
            events=self._events)
        # consensus-stage device residency: norm_counts / tpm staged to HBM
        # once and reused across the three refits and the K-selection sweep
        self._dev_cache: dict = {}
        # shape-sets whose consensus programs were already warm-dispatched
        self._warmed: set = set()
        # shared dummy arrays for program warming, keyed by shape: without
        # this, warming many Ks concurrently would allocate one full
        # (cells x genes) ones-array PER K — an HBM spike the serial path
        # never had
        import threading

        self._warm_lock = threading.Lock()
        self._warm_dummies: dict = {}
        # ||X||^2 for the stats-path prediction error, keyed by content
        # token: identical for every K of a selection sweep, and a full
        # O(n*g) host f64 pass each time otherwise
        self._x_sq_cache: dict = {}

    # dense HBM bytes above which consensus matrices are NOT kept resident
    # (atlas-scale consensus uses the row-sharded streaming refits instead)
    _DEV_CACHE_BUDGET_BYTES = 2 << 30

    def _stageable(self, X) -> bool:
        n, g = X.shape
        return (n < self.rowshard_threshold
                and n * g * 4 <= self._DEV_CACHE_BUDGET_BYTES)

    @staticmethod
    def _content_token(X) -> tuple:
        """Cheap content fingerprint so the residency cache can tell two
        same-shape matrices apart (consensus accepts a caller-supplied
        norm_counts): shape + nnz + f64 sum + a strided 64-element sample.
        O(nnz) for the sum — microseconds next to a host->device transfer."""
        buf = X.data if sp.issparse(X) else np.asarray(X).ravel()
        step = max(1, buf.size // 64)
        return (tuple(X.shape), int(getattr(X, "nnz", buf.size)),
                float(buf.sum(dtype=np.float64)),
                buf[::step][:64].astype(np.float64).tobytes())

    def _stage_dense(self, key: str, X):
        """Stage a host matrix to a device f32 array once per artifact and
        reuse it for every subsequent consensus refit in this process (the
        reference re-enters torch — and we'd otherwise re-cross the host
        link — once per refit; X never changes between them, SURVEY §3.3).
        Entries are validated by a content fingerprint, not just shape.
        Returns X unchanged when it exceeds the residency budget or the
        row-sharded paths will handle it.

        Uploads run through the pipelined staging engine
        (``parallel.streaming``): sparse inputs ship CSR slabs and densify
        on device — the full dense matrix never exists on host — and the
        per-phase walls/bytes land in the timings ledger."""
        import jax

        from ..parallel.streaming import StreamStats, stream_to_device

        if not self._stageable(X):
            return X
        token = self._content_token(X)
        ent = self._dev_cache.get(key)
        if ent is not None and ent[0] == token:
            return ent[1]
        stats = StreamStats()
        Xd = jax.block_until_ready(
            stream_to_device(X, stats=stats, events=self._events))
        stats.record_to(self._timer, f"stage_dense:{key}")
        self._events.emit_stream(f"stage_dense:{key}", stats)
        self._dev_cache[key] = (token, Xd)
        return Xd

    # ------------------------------------------------------------------
    # prepare
    # ------------------------------------------------------------------

    @_timed("prepare")
    def prepare(self, counts_fn, components, n_iter=100, densify=False,
                tpm_fn=None, seed=None, beta_loss="frobenius",
                num_highvar_genes=2000, genes_file=None, alpha_usage=0.0,
                alpha_spectra=0.0, init="random", total_workers=-1,
                use_gpu=False, batch_size=5000, max_NMF_iter=1000):
        """Load counts, select HVGs, variance-normalize, and write the
        replicate ledger + solver config (``cnmf.py:458-596``).

        ``use_gpu`` is accepted for contract compatibility; device placement
        is JAX's job (the flag is persisted to the YAML so artifacts stay
        comparable with reference runs).
        """
        input_counts = load_counts(counts_fn, densify=densify)

        from ..ops.stats import (cell_scale_factors, column_moments_staged,
                                 row_sums)

        if tpm_fn is None:
            # TPM = diag(1e6/rowsum) @ counts: its moments AND the raw-count
            # moments (gene scaling, cnmf.py:674-679) come from ONE fused
            # pass over the counts, and the row totals are computed once for
            # both the TPM artifact and the moment pass
            totals = row_sums(input_counts.X)
            tpm_scale = cell_scale_factors(totals, 1e6)
            tpm = compute_tpm(input_counts, totals=totals)
            write_h5ad(self.paths["tpm"], tpm)
            counts_moments, tpm_moments = column_moments_staged(
                input_counts.X, row_scale=tpm_scale)
        else:
            if tpm_fn.endswith((".h5ad", ".mtx", ".mtx.gz")):
                tpm = load_counts(tpm_fn, densify=False)
            else:
                tpm = load_counts(tpm_fn, densify=densify)
            write_h5ad(self.paths["tpm"], tpm)
            # separate TPM file: two unrelated matrices, one staged pass each
            tpm_moments, _ = column_moments_staged(tpm.X)
            counts_moments, _ = column_moments_staged(input_counts.X)

        # per-gene TPM mean/std, population moments (ddof=0) on both the
        # sparse and dense paths (cnmf.py:570-580)
        gene_tpm_mean, gene_tpm_var = tpm_moments
        input_tpm_stats = pd.DataFrame(
            [gene_tpm_mean, np.sqrt(gene_tpm_var)],
            index=["__mean", "__std"], columns=tpm.var.index,
        ).T
        save_df_to_npz(input_tpm_stats, self.paths["tpm_stats"])

        if genes_file is not None:
            highvargenes = open(genes_file).read().rstrip().split("\n")
        else:
            highvargenes = None

        norm_counts = self.get_norm_counts(
            input_counts, tpm, num_highvar_genes=num_highvar_genes,
            high_variance_genes_filter=highvargenes,
            tpm_moments=tpm_moments, counts_var0=counts_moments[1])
        self.save_norm_counts(norm_counts)

        replicate_params, run_params = self.get_nmf_iter_params(
            ks=components, n_iter=n_iter, random_state_seed=seed,
            beta_loss=beta_loss, alpha_usage=alpha_usage,
            alpha_spectra=alpha_spectra, init=init,
            total_workers=total_workers, use_gpu=use_gpu,
            batch_size=batch_size, max_iter=max_NMF_iter)
        self.save_nmf_iter_params(replicate_params, run_params)

    def get_norm_counts(self, counts, tpm, high_variance_genes_filter=None,
                        num_highvar_genes=None, tpm_moments=None,
                        counts_var0=None):
        """HVG subset + unit-variance gene scaling WITHOUT centering
        (``cnmf.py:624-698``); raises on cells with zero HVG counts.

        ``tpm_moments`` / ``counts_var0``: optional precomputed TPM (mean,
        var) and raw-count population variance over ALL genes — prepare()
        derives both from one staged device pass; a column's moments are
        unchanged by subsetting, so the HVG slice reuses them directly.
        """
        if high_variance_genes_filter is None:
            gene_stats, _ = highvar_genes(tpm.X, numgenes=num_highvar_genes,
                                          precomputed_moments=tpm_moments)
            high_variance_genes_filter = list(
                tpm.var.index[gene_stats.high_var.values])

        norm_counts = counts[:, high_variance_genes_filter].copy()
        # no f64 working copy (ISSUE 10 satellite): the old
        # ``astype(np.float64)`` here doubled prepare's peak host memory
        # for a matrix every solver consumes as f32/bf16. Float64 now
        # lives ONLY in the column-moment accumulators (ops/stats.py) and
        # the per-quotient division; the stored values are the f32
        # rounding of the exact f64 quotients — bit-identical to staging
        # the old f64 artifact (integer counts are f32-exact).

        n = counts.X.shape[0]
        sub_var1 = None
        if counts_var0 is not None and n > 1:
            pos = counts.var.index.get_indexer(high_variance_genes_filter)
            if (pos >= 0).all():
                sub_var1 = np.asarray(counts_var0)[pos] * (n / (n - 1))

        if sp.issparse(tpm.X):
            # sparse path: zero-variance genes pass through unchanged
            # (sc.pp.scale semantics, cnmf.py:675)
            norm_counts.X, _ = scale_columns(norm_counts.X, ddof=1,
                                             zero_std_to_one=True,
                                             precomputed_var=sub_var1,
                                             out_dtype=np.float32)
            if np.isnan(norm_counts.X.data).sum() > 0:
                print("Warning NaNs in normalized counts matrix")
        else:
            # dense path: division by a zero std produces NaN; the reference
            # only warns (cnmf.py:679)
            norm_counts.X, _ = scale_columns(norm_counts.X, ddof=1,
                                             zero_std_to_one=False,
                                             precomputed_var=sub_var1,
                                             out_dtype=np.float32)
            if np.isnan(norm_counts.X).sum().sum() > 0:
                print("Warning NaNs in normalized counts matrix")

        with atomic_artifact(self.paths["nmf_genes_list"]) as tmp:
            with open(tmp, "w") as f:
                f.write("\n".join(high_variance_genes_filter))

        zerocells = np.asarray(norm_counts.X.sum(axis=1) == 0).reshape(-1)
        if zerocells.sum() > 0:
            examples = norm_counts.obs.index[np.ravel(zerocells)]
            raise Exception(
                "Error: %d cells have zero counts of overdispersed genes. "
                "E.g. %s. Filter those cells and re-run or adjust the number "
                "of overdispersed genes. Quitting!"
                % (zerocells.sum(), ", ".join(examples[:4])))
        return norm_counts

    def save_norm_counts(self, norm_counts):
        """Persist the normalized matrix: the h5ad artifact and/or the
        out-of-core row-slab shard store (ISSUE 10, utils/shardstore.py).

        ``CNMF_TPU_OOC=auto`` (default) additionally writes the store
        when the matrix's host footprint exceeds the slab budget —
        factorize workers then stream only their own row-range slabs
        from disk instead of each materializing the full matrix.
        ``=1`` forces the store AND makes it authoritative: the h5ad
        normalized-counts copy is SKIPPED (the two used to double-write
        the matrix), with the fallback noted loudly here and in the
        factorize provenance. ``=0`` keeps the h5ad-only legacy path.
        A store the current mode does not write is REMOVED — a stale
        store from an earlier prepare must never hijack factorize."""
        from ..utils import shardstore

        # a re-prepare invalidates any consensus-stage device residency
        self._dev_cache.clear()
        mode = shardstore.ooc_mode()
        write_store = mode == "1" or (
            mode == "auto"
            and shardstore.host_matrix_bytes(norm_counts.X)
            > shardstore.ooc_budget_bytes())
        # remove-store -> write-h5ad -> write-store ordering: each write
        # is individually atomic, so a crash at ANY point leaves a
        # consistent pair — h5ad-only, store-only (OOC=1), or both from
        # the same prepare. A stale store can then only predate this
        # protocol (or be tampered with), which worker 0's fresh-run
        # sweep catches via the metadata cross-check (_store_stale).
        shardstore.remove_store(self.paths["shard_store"])
        if mode == "1" and write_store:
            # the store is authoritative: skip the h5ad double-write (a
            # second full copy of the matrix on disk + a second full
            # serialization pass). Remove any stale copy so no reader
            # can fall back to an older prepare's matrix.
            print("prepare: CNMF_TPU_OOC=1 — normalized counts live in "
                  "the shard store only (h5ad copy skipped); consensus "
                  "and k-selection stream it slab-wise, resident legacy "
                  "readers assemble loudly.")
            try:
                os.unlink(self.paths["normalized_counts"])
            except OSError:
                pass
        else:
            write_h5ad(self.paths["normalized_counts"], norm_counts)
        if write_store:
            with self._timer.stage("prepare.shard_store"):
                shardstore.write_shard_store(
                    self.paths["shard_store"], norm_counts.X,
                    obs_names=norm_counts.obs.index,
                    var_names=norm_counts.var.index, events=self._events)

    def _probe_store(self):
        """The shard store for this run, or ``None`` (absent, invalid, or
        ``CNMF_TPU_OOC=0``)."""
        from ..utils import shardstore

        if shardstore.ooc_mode() == "0":
            return None
        store, _reason = shardstore.probe_shard_store(
            self.paths["shard_store"], events=self._events)
        return store

    def _read_norm_counts(self, store=None):
        """The normalized counts as an AnnDataLite: the h5ad when it
        exists, else assembled from the shard store (the authoritative
        source under ``CNMF_TPU_OOC=1``) — loudly, since assembly
        materializes the full matrix on host and callers above the slab
        budget should stream instead."""
        if os.path.exists(self.paths["normalized_counts"]):
            return read_h5ad(self.paths["normalized_counts"])
        if store is None:
            store = self._probe_store()
        if store is None:
            from ..utils import shardstore

            # a store directory that EXISTS but failed validation, with
            # no h5ad to fall back to, deserves its own diagnosis — the
            # raw h5ad FileNotFoundError would point at the wrong artifact
            _, reason = shardstore.probe_shard_store(
                self.paths["shard_store"])
            if reason is not None and reason != "missing":
                raise shardstore.TornShardError(
                    "normalized counts are unreadable: the h5ad copy is "
                    "absent (store-authoritative prepare) and the shard "
                    "store failed validation — re-run prepare. (%s)"
                    % reason)
            # no store and no h5ad: surface the h5ad error path callers
            # have always seen
            return read_h5ad(self.paths["normalized_counts"])
        warnings.warn(
            "normalized_counts h5ad is absent (CNMF_TPU_OOC=1 store-"
            "authoritative prepare); assembling the full matrix from the "
            "shard store on host — streaming consumers should pass the "
            "store instead", RuntimeWarning, stacklevel=2)
        return self._store_anndata(store, with_matrix=True)

    @staticmethod
    def _store_anndata(store, with_matrix=False):
        """AnnDataLite view of a shard store: metadata always (shape +
        obs/var names — what factorize's dispatch and artifact writers
        need); the matrix itself only on request (``with_matrix`` — the
        fits-in-budget path), otherwise an all-zero CSR placeholder of
        the right shape that no solver ever consumes."""
        X = (store.to_matrix() if with_matrix
             else sp.csr_matrix(store.shape, dtype=np.float32))
        obs = pd.DataFrame(index=pd.Index(store.obs_names()
                                          or [str(i) for i in
                                              range(store.shape[0])]))
        var = pd.DataFrame(index=pd.Index(store.var_names()
                                          or [str(j) for j in
                                              range(store.shape[1])]))
        return AnnDataLite(X, obs=obs, var=var)

    def _store_stale(self, store) -> bool:
        """True when the store disagrees with the current prepare's h5ad
        on shape or gene index — metadata-only reads on both sides, so
        the check never materializes a matrix. (``save_norm_counts``
        orders remove-store -> write-h5ad -> write-store, so a crash can
        only leave consistent pairs; this catches pre-crash debris and
        manual tampering.) With no h5ad the store is authoritative
        (``CNMF_TPU_OOC=1``) and never stale by this test."""
        from ..utils.anndata_lite import peek_h5ad_shape, peek_h5ad_var_names

        path = self.paths["normalized_counts"]
        if not os.path.exists(path):
            return False
        try:
            if peek_h5ad_shape(path) != store.shape:
                return True
            h5_var = peek_h5ad_var_names(path)
            return (h5_var is not None
                    and list(h5_var) != list(store.var_names()))
        except Exception as exc:
            warnings.warn(
                "shard store staleness probe failed (%s); treating the "
                "store as stale" % (exc,), RuntimeWarning, stacklevel=2)
            return True

    def _sweep_stale_store(self, store) -> bool:
        """Worker 0's fresh-run sweep (ISSUE 10 satellite): remove
        orphaned shard-store atomic-write temps, and delete a store whose
        manifest mismatches the current prepare so it can never hijack
        this run's ingestion. True when the store was removed (callers
        must fall back to the h5ad)."""
        from ..utils import shardstore

        shardstore.sweep_store_temps(self.paths["shard_store"])
        if store is not None and self._store_stale(store):
            warnings.warn(
                "shard store at %s does not match the current prepare's "
                "normalized_counts h5ad — removing the stale store "
                "(factorize falls back to the h5ad)"
                % self.paths["shard_store"], RuntimeWarning, stacklevel=2)
            shardstore.remove_store(self.paths["shard_store"])
            return True
        return False

    # ------------------------------------------------------------------
    # replicate ledger + solver config
    # ------------------------------------------------------------------

    def get_nmf_iter_params(self, ks, n_iter=100, random_state_seed=None,
                            beta_loss="kullback-leibler", alpha_usage=0.0,
                            alpha_spectra=0.0, init="random",
                            total_workers=-1, use_gpu=False, batch_size=5000,
                            max_iter=1000):
        """Cartesian (K x iter) task ledger with derived per-run seeds and
        the persisted solver kwargs (``cnmf.py:701-777``).

        Seed derivation is pinned to the reference exactly (the golden tests
        compare [n_components, iter, nmf_seed] element-wise,
        ``tests/test_reproducibility.py:160-165``): a master-seeded
        ``np.random.randint(1, 2**31-1)`` draw of ``len(ks) * n_iter`` values
        consumed in ``product(sorted(set(ks)), range(n_iter))`` order. The
        draw length uses the *unsorted, undeduped* ks — reproducing the
        reference's over-draw so seeds match even for duplicate-K input.
        """
        if isinstance(ks, int):
            ks = [ks]
        k_list = sorted(set(list(ks)))

        n_runs = len(ks) * n_iter
        np.random.seed(seed=random_state_seed)
        nmf_seeds = np.random.randint(low=1, high=(2 ** 31) - 1, size=n_runs)

        import itertools

        replicate_params = []
        for i, (k, r) in enumerate(itertools.product(k_list, range(n_iter))):
            completed = os.path.exists(self.paths["iter_spectra"] % (k, r))
            replicate_params.append([k, r, nmf_seeds[i], completed])
        replicate_params = pd.DataFrame(
            replicate_params,
            columns=["n_components", "iter", "nmf_seed", "completed"])

        n_completed = replicate_params["completed"].sum()
        if n_completed > 0:
            warnings.warn(
                "{n} runs already appear completed. If this is unexpected, "
                "consider re-initializing the cnmf object with a different "
                "run name or output directory".format(n=n_completed),
                UserWarning)

        # the persisted solver-kwargs schema is golden-tested by the
        # reference (recursive dict equality); key set and values match
        # cnmf.py:757-771 — alpha_W/alpha_H are switched w.r.t. sklearn
        _nmf_kwargs = dict(
            alpha_W=alpha_spectra,
            alpha_H=alpha_usage,
            l1_ratio_H=0.0,
            l1_ratio_W=0.0,
            beta_loss=beta_loss,
            algo="mu",
            tol=1e-4,
            mode="online",
            online_chunk_max_iter=max_iter,
            online_chunk_size=batch_size,
            init=init,
            n_jobs=total_workers,
            use_gpu=use_gpu,
        )
        return replicate_params, _nmf_kwargs

    def update_nmf_iter_params(self):
        """Re-probe iter_spectra files to refresh the completed column
        (``cnmf.py:780-795``). Must not run while factorize workers are
        active (undocumented reference invariant, SURVEY.md §5.2)."""
        _nmf_kwargs = self._solver_params()
        replicate_params = load_df_from_npz(
            self.paths["nmf_replicate_parameters"])
        for i in replicate_params.index:
            replicate_params.at[i, "completed"] = os.path.exists(
                self.paths["iter_spectra"]
                % (replicate_params.at[i, "n_components"],
                   replicate_params.at[i, "iter"]))
        remaining = (replicate_params["completed"] == False).sum()  # noqa: E712
        print("{n} NMF runs are currently incomplete".format(n=remaining))
        self.save_nmf_iter_params(replicate_params, _nmf_kwargs)

    def save_nmf_iter_params(self, replicate_params, run_params):
        # the ledger summary must ride the manifest, which flushes with the
        # FIRST event (prepare's own stage event beats factorize to it)
        self._set_ledger_manifest(replicate_params, run_params)
        save_df_to_npz(replicate_params,
                       self.paths["nmf_replicate_parameters"])
        with atomic_artifact(self.paths["nmf_run_parameters"]) as tmp:
            with open(tmp, "w") as f:
                yaml.dump(run_params, f)

    def _set_ledger_manifest(self, replicate_params, nmf_kwargs,
                             n_worker_tasks=None):
        """Seed/K summary for the telemetry manifest (utils/telemetry.py):
        called from prepare (ledger creation) and factorize (covers
        factorize-only workers, whose cNMF object never saw prepare)."""
        if not self._events.enabled or not len(replicate_params):
            return
        ledger = {
            "ks": sorted(set(int(v)
                             for v in replicate_params.n_components)),
            "n_tasks": int(len(replicate_params)),
            "seed_min": int(replicate_params.nmf_seed.min()),
            "seed_max": int(replicate_params.nmf_seed.max()),
            "beta_loss": str(nmf_kwargs.get("beta_loss")),
            "init": str(nmf_kwargs.get("init", "random")),
            "mode": str(nmf_kwargs.get("mode", "online"))}
        if n_worker_tasks is not None:
            ledger["n_worker_tasks"] = int(n_worker_tasks)
        self._events.set_manifest_extra(ledger=ledger)

    # ------------------------------------------------------------------
    # factorize
    # ------------------------------------------------------------------

    def _nmf(self, X, nmf_kwargs):
        """Single-replicate solve; returns ``(spectra, usages, err)``
        (``cnmf.py:805-821``; the final objective rides along as the
        per-replicate health signal, ``ops.nmf.lane_health``)."""
        kwargs = {k: v for k, v in nmf_kwargs.items() if k != "n_jobs"}
        usages, spectra, err = run_nmf(X, **kwargs)
        return spectra, usages, err

    @_timed("factorize")
    def factorize(self, worker_i=0, total_workers=1,
                  skip_completed_runs=False, batched=True, mesh=None,
                  replicates_per_batch=None, rowshard=None,
                  rowshard_threshold: int | None = None, packed=None,
                  mesh_shape=None):
        """Run this worker's share of the replicate ledger.

        Contract-compatible with the reference (``cnmf.py:839-892``):
        round-robin ``worker_filter`` sharding, per-(k, iter) spectra files.

        TPU-first execution (``batched=True``, the default): tasks are
        grouped per K and each group runs as ONE vmapped XLA call, sharded
        over ``mesh`` when given (defaults to all local devices) — the
        reference's outer Python process loop becomes a batched device
        program. ``batched=False`` preserves the sequential per-task path.

        ``packed`` (default auto): runs a multi-K ``init='random'`` sweep
        as ONE compiled program at K_max with zero-padded components — MU
        provably keeps the padding at zero, so per-seed spectra match the
        per-K programs bit-for-bit at matched batch shapes
        (``tests/test_parallel.py``). Auto engages it only for
        compile-dominated quick scans (>= 4 Ks, <= 32 replicates/K):
        production-scale sweeps measured ~13% slower packed (K_max padding
        costs real FLOPs once replicates amortize X reads) while the per-K
        programs' compiles are already concurrently warmed. ``packed=True``
        / ``packed=False`` force either path (CLI ``--per-k-programs``
        forces per-K).

        Atlas-scale inputs (``rowshard=True``, or auto when
        ``n_cells >= rowshard_threshold``; BASELINE config 5): instead of
        replicating a densified X to every device, the cells axis is sharded
        across the mesh — CSR row blocks stream host→HBM one shard at a time
        (never a host dense copy), the staged device array is reused across
        all replicates, and each replicate's W statistics psum over ICI.

        Fault tolerance (ISSUE 5, ``runtime/resilience.py``): every
        replicate is health-graded (``ops.nmf.lane_health`` — host-side,
        zero program changes); unhealthy lanes are retried with derived
        seeds (``seed XOR attempt``, up to ``CNMF_TPU_MAX_RETRIES``) and
        quarantined into the per-worker resilience ledger when the budget
        runs out, with a hard failure below ``CNMF_TPU_MIN_HEALTHY_FRAC``
        survivors per K. ``skip_completed_runs`` probes AND validates
        artifacts (torn files rerun), and resumes the batched paths at
        whole-K-group granularity so a resumed sweep is bit-identical to
        an uninterrupted one. (The 2-D multi-host path keeps the plain
        write path: cross-host retry coordination is out of scope.)

        ``mesh_shape`` (ISSUE 13): named execution-layout dispatch —
        ``'1d'``/``'rowshard'`` forces the 1-D cells mesh, ``'2d'`` the
        (replicates x cells) mesh, ``'grid2d'`` the true 2-D
        (cells x genes) processor grid (``parallel/grid2d.py``: X
        sharded over both axes, W over genes, H over cells, statistics
        collectives axis-local and compute-overlapped). A ``Mesh`` with
        axes ``('cells', 'genes')`` passed as ``mesh`` routes to the
        grid too.
        """
        # observability shell (obs/): the implementation below has many
        # early returns (2-D mesh, grid, rowshard, resume-noop), so the
        # worker-level trace span + the end-of-factorize metrics
        # snapshot live in this wrapper's finally. Both are no-ops
        # unless their knobs are set; neither touches compiled programs.
        from ..obs import metrics as obs_metrics
        from ..obs import tracing as obs_tracing

        obs_metrics.counter_inc("cnmf_factorize_workers_total")
        # launcher-planted ambient context when present; a direct class-driven
        # run mints its own root so sampled runs always trace
        ctx = obs_tracing.child(obs_tracing.process_context())
        if ctx is None:
            ctx = obs_tracing.new_trace()
        t0 = time.perf_counter()
        try:
            return self._factorize_impl(
                worker_i=worker_i, total_workers=total_workers,
                skip_completed_runs=skip_completed_runs, batched=batched,
                mesh=mesh, replicates_per_batch=replicates_per_batch,
                rowshard=rowshard, rowshard_threshold=rowshard_threshold,
                packed=packed, mesh_shape=mesh_shape)
        finally:
            obs_tracing.emit_span(
                self._events, ctx, "factorize.worker",
                obs_tracing.perf_to_wall(t0),
                (time.perf_counter() - t0) * 1e3,
                worker=int(worker_i))
            obs_metrics.emit_snapshot(self._events)

    def _factorize_impl(self, worker_i=0, total_workers=1,
                        skip_completed_runs=False, batched=True, mesh=None,
                        replicates_per_batch=None, rowshard=None,
                        rowshard_threshold: int | None = None, packed=None,
                        mesh_shape=None):
        from ..runtime import faults, resilience

        # declarative plan replay (ISSUE 17, runtime/planner.py):
        # CNMF_TPU_PLAN=<file> (the CLI's --plan) pins the WHOLE dispatch
        # surface to a previously dumped plan BEFORE any knob below
        # resolves — every scattered consumer then reproduces that run's
        # dispatch bit-identically. A missing or invalid plan file raises
        # here rather than silently running a different dispatch.
        from ..runtime.planner import maybe_apply_plan_env

        maybe_apply_plan_env()

        # named layout dispatch (ISSUE 13): validated up front, before
        # any ledger/matrix IO — a bad or conflicting layout request
        # must fail in milliseconds, not after loading artifacts
        if mesh_shape is not None and mesh_shape not in (
                "1d", "rowshard", "2d", "grid2d", "grid"):
            raise ValueError(
                f"mesh_shape={mesh_shape!r}: expected '1d'/'rowshard', "
                "'2d' (replicates x cells), or 'grid2d' (cells x genes)")
        wants_2d_mesh = (mesh == "2d" or (
            hasattr(mesh, "axis_names")
            and tuple(mesh.axis_names) == ("replicates", "cells")))
        if mesh_shape in ("1d", "rowshard"):
            if wants_2d_mesh:
                # same loud-conflict invariant as grid-vs-2d below: an
                # explicit 1-D request must never silently run the
                # (replicates x cells) path
                raise ValueError(
                    "conflicting execution layouts: mesh requests the "
                    "(replicates x cells) mesh while mesh_shape "
                    "requests the 1-D cells mesh — pass one of them")
            rowshard = True
        elif mesh_shape == "2d" and mesh is None:
            mesh = "2d"
        grid = (mesh == "grid2d" or mesh_shape in ("grid2d", "grid")
                or (hasattr(mesh, "axis_names")
                    and tuple(mesh.axis_names) == ("cells", "genes")))
        if grid and wants_2d_mesh:
            # conflicting layout requests (e.g. --mesh-2d --mesh-grid2d)
            # must fail loudly, not silently drop one of them
            raise ValueError(
                "conflicting execution layouts: mesh requests the "
                "(replicates x cells) mesh while mesh_shape requests the "
                "(cells x genes) grid — pass one of them")
        grid_mesh = mesh if grid and hasattr(mesh, "axis_names") else None

        run_params = load_df_from_npz(self.paths["nmf_replicate_parameters"])
        # out-of-core ingestion (ISSUE 10, utils/shardstore.py): when a
        # shard store exists (and CNMF_TPU_OOC != 0), factorize defers
        # materializing the matrix — the rowshard/2-D paths stream slabs
        # straight from disk with host residency bounded by
        # CNMF_TPU_OOC_BUDGET_BYTES, and only the resident solver paths
        # load/assemble the full matrix (below, once dispatch is known)
        store = self._probe_store()
        if store is not None:
            norm_counts = self._store_anndata(store)
        elif os.path.exists(self.paths["normalized_counts"]):
            norm_counts = read_h5ad(self.paths["normalized_counts"])
        else:
            # no valid store AND no h5ad: _read_norm_counts raises the
            # torn-store diagnosis (or the classic h5ad error)
            norm_counts = self._read_norm_counts()
        _nmf_kwargs = self._solver_params()

        my_tasks = list(worker_filter(range(len(run_params)), worker_i,
                                      total_workers))
        quarantined_idx: dict[int, int | None] = {}  # task idx -> attempts
        if not skip_completed_runs:
            jobs = my_tasks
            if int(worker_i) == 0:
                # a fresh run recomputes every replicate, voiding prior
                # quarantine records; in-range workers rewrite/remove
                # their own ledgers at finalize, but ledgers from a run
                # with MORE workers have no owner — sweep them here so
                # their stale records can't haunt later resumes/combines
                resilience.sweep_stale_ledgers(
                    self.paths["resilience_ledger"],
                    max(int(total_workers), 1))
                # ISSUE 10 satellite: also sweep shard-store debris — a
                # killed prepare's atomic-write temps, and a stale store
                # whose manifest no longer matches the current prepare
                # (it must never hijack this run's ingestion)
                if self._sweep_stale_store(store):
                    store = None
                    norm_counts = read_h5ad(
                        self.paths["normalized_counts"])
        else:
            # torn-artifact-proof resume: probe AND validate the on-disk
            # artifacts of this worker's own ledger shard. The persisted
            # `completed` column is stale unless prepare re-ran, and a
            # SIGKILL mid-write used to leave truncated npz files the
            # column then trusted; a torn file counts as incomplete here
            # and its rerun overwrites it atomically. (Divergence from
            # the reference's resume, which re-round-robins the
            # incomplete SUBSET across workers: a respawned worker must
            # resume exactly its own unfinished shard while its peers
            # keep running theirs.)
            quarantined_prev = resilience.load_quarantine_records(
                self.paths["resilience_ledger"])
            jobs = []
            # torn-artifact events are deferred past _set_ledger_manifest
            # below: the FIRST emit flushes the telemetry manifest, and
            # emitting here would flush it without its ledger block
            deferred_torn: list[dict] = []
            for idx in my_tasks:
                p = run_params.iloc[idx, :]
                k_t, it_t = int(p["n_components"]), int(p["iter"])
                fn = self.paths["iter_spectra"] % (k_t, it_t)
                reason = resilience.probe_spectra_file(
                    fn, k=k_t, n_genes=int(norm_counts.X.shape[1]))
                if reason is None:
                    continue
                if (k_t, it_t) in quarantined_prev:
                    attempts_prev = quarantined_prev[(k_t, it_t)]
                    if (attempts_prev is not None
                            and attempts_prev < resilience.max_retries()):
                        # the quarantine warning tells users to raise
                        # CNMF_TPU_MAX_RETRIES — honor it: under a larger
                        # budget the record is not final, so the lane
                        # reruns with the full new retry ladder
                        jobs.append(idx)
                        continue
                    # deliberately absent: a previous run exhausted this
                    # lane's retry budget. Without this check every
                    # resume would rerun (and re-quarantine) it forever —
                    # resume after a degraded run must be idempotent.
                    quarantined_idx[idx] = attempts_prev
                    continue
                if reason != "missing":
                    warnings.warn(
                        "resume: replicate artifact failed validation and "
                        "will be rerun — %s" % reason,
                        RuntimeWarning, stacklevel=2)
                    deferred_torn.append({"path": fn, "reason": reason})
                jobs.append(idx)

        # n_worker_tasks counts the tasks NEEDING RECOVERY on a resume
        # (pre-expansion): the whole-K-group expansion below may rerun
        # more replicates for bit-parity, and those surface as ordinary
        # per-replicate convergence records in the event stream
        self._set_ledger_manifest(run_params, _nmf_kwargs,
                                  n_worker_tasks=len(jobs))
        if skip_completed_runs:
            for ctx in deferred_torn:
                self._events.emit("fault", kind="torn_artifact", context=ctx)
        if store is not None:
            # emitted only now: the FIRST emit flushes the telemetry
            # manifest, which must carry the ledger block set just above
            self._events.emit(
                "dispatch", decision="ooc_ingest",
                context={"slabs": len(store.slabs),
                         "store_bytes": int(store.store_bytes),
                         "format": store.format,
                         "rows": int(store.n_rows),
                         "backend": getattr(getattr(store, "backend", None),
                                            "kind", "local"),
                         "h5ad_present": os.path.exists(
                             self.paths["normalized_counts"])})

        # 2-D replicates x cells mesh (multi-host layout, parallel/multihost):
        # mesh="2d" auto-builds it; a Mesh with those two axes routes as-is
        if not grid and (
                mesh == "2d"
                or (hasattr(mesh, "axis_names")
                    and tuple(mesh.axis_names) == ("replicates", "cells"))):
            from ..parallel import mesh_2d

            if mesh == "2d":
                mesh = mesh_2d()
            self._factorize_2d(jobs, run_params, norm_counts, _nmf_kwargs,
                               mesh, worker_i, replicates_per_batch,
                               store=store)
            return

        # quarantine + reseeded-retry bookkeeping (runtime/resilience.py):
        # every single-controller factorize path reports per-replicate
        # health through this guard; unhealthy lanes retry with derived
        # seeds and exhausted lanes quarantine (excluded from combine via
        # the resilience ledger). The 2-D multi-host path above is exempt:
        # retries there would have to be coordinated collectives.
        guard = resilience.ReplicateGuard(
            events=self._events,
            ledger_path=self.paths["resilience_ledger"] % int(worker_i))

        # liveness (ISSUE 8): every factorize path stamps progress under
        # CNMF_TPU_HEARTBEAT_S so the launcher's straggler containment
        # (and a pod's barrier diagnosis) can tell "slow but working"
        # from "wedged" — the rowshard path additionally beats per pass
        from ..runtime import elastic as _elastic

        heartbeat = None
        if _elastic.heartbeat_s() > 0:
            heartbeat = _elastic.Heartbeat(
                os.path.dirname(self.paths["resilience_ledger"]),
                self.name, int(worker_i), events=self._events)

        def _credit_completed(final_jobs):
            # resume accounting: replicates already valid on disk count as
            # healthy toward the per-K min-healthy-frac floor — without
            # the credit a resume that reruns 1 of N replicates and
            # quarantines it would hard-fail at 0/1 observed when the K
            # is really (N-1)/N healthy
            if not skip_completed_runs:
                return
            per_k: dict[int, int] = {}
            for i in set(my_tasks) - set(final_jobs):
                p = run_params.iloc[i, :]
                kk = int(p["n_components"])
                if i in quarantined_idx:
                    # still-unresolved quarantine, not rerun this session:
                    # counts toward the total (not healthy) and rides into
                    # the rewritten ledger so combine keeps excluding it
                    guard.carry_quarantined(kk, int(p["iter"]),
                                            int(p["nmf_seed"]),
                                            attempts=quarantined_idx[i])
                else:
                    per_k[kk] = per_k.get(kk, 0) + 1
            for kk, n in per_k.items():
                guard.credit_existing(kk, n)

        if skip_completed_runs and not jobs:
            # nothing to re-solve — but the floor accounting must still
            # run: a resume after a below-floor run would otherwise exit
            # 0 here and let the pipeline proceed to the exact degraded
            # consensus the UNHEALTHY_EXIT_CODE plumbing aborts on.
            # Credits + carried quarantines reproduce the K's true state;
            # finalize re-evaluates the floor and rewrites the ledger.
            _credit_completed(jobs)
            guard.finalize()
            print("[Worker %d]. All assigned replicates already have valid "
                  "artifacts%s; nothing to resume."
                  % (worker_i, " or quarantine records"
                     if quarantined_idx else ""))
            return

        if grid:
            # true 2-D (cells x genes) grid (ISSUE 13): the rowshard
            # execution shell (sequential replicates, checkpoint/
            # heartbeat/hostloss contracts, resilience guard) over the
            # grid solver — stage once sharded over BOTH axes, solve
            # each replicate with axis-local overlapped collectives
            _credit_completed(jobs)
            self._factorize_rowsharded(jobs, run_params, norm_counts,
                                       _nmf_kwargs, grid_mesh, worker_i,
                                       guard=guard,
                                       resume=skip_completed_runs,
                                       heartbeat=heartbeat, store=store,
                                       grid=True)
            return

        if rowshard_threshold is None:
            rowshard_threshold = self.rowshard_threshold
        if rowshard is None:
            # auto-engage only for the default batched path: an explicit
            # batched=False / --sequential request keeps its solver
            rowshard = (batched
                        and norm_counts.X.shape[0] >= int(rowshard_threshold))
            if rowshard:
                print("factorize: %d cells >= rowshard threshold %d — "
                      "auto-engaging the row-sharded solver (pass "
                      "rowshard=False / --no-rowshard to keep the batched "
                      "replicate sweep)."
                      % (norm_counts.X.shape[0], int(rowshard_threshold)))
        if rowshard:
            _credit_completed(jobs)
            self._factorize_rowsharded(jobs, run_params, norm_counts,
                                       _nmf_kwargs, mesh, worker_i,
                                       guard=guard,
                                       resume=skip_completed_runs,
                                       heartbeat=heartbeat, store=store)
            return

        if store is not None:
            # resident solver paths (batched/sequential) need the matrix
            # on host: the h5ad when prepare kept it (bit-identical, no
            # store read), else assembled from the store (CNMF_TPU_OOC=1,
            # loud — streaming consumers take the rowshard path above)
            norm_counts = self._read_norm_counts(store)

        if not batched:
            _credit_completed(jobs)
            # the sequential lane solves through run_nmf, which resolves
            # the same env-driven recipe per call — record it once here so
            # sequential provenance matches the batched lane's. The ell
            # flag (it feeds the amu cost-ratio rho) comes from run_nmf's
            # own dispatch helper, so the recorded recipe is exactly the
            # one every task will engage.
            from ..ops.nmf import run_nmf_use_ell
            from ..ops.recipe import resolve_recipe as _resolve_recipe

            _seq_beta = beta_loss_to_float(_nmf_kwargs["beta_loss"])
            _seq_ell = run_nmf_use_ell(
                norm_counts.X, _seq_beta,
                init=_nmf_kwargs.get("init", "random"),
                algo=_nmf_kwargs.get("algo", "mu"),
                fp_precision=_nmf_kwargs.get("fp_precision", "float"))
            _seq_recipe = _resolve_recipe(
                _seq_beta, _nmf_kwargs.get("mode", "online"),
                algo=_nmf_kwargs.get("algo", "mu"), ell=_seq_ell)
            self._events.emit("dispatch", decision="solver_recipe",
                              context=_seq_recipe.as_context())
            self._save_factorize_provenance(
                "sequential", worker_i,
                dict({k: v for k, v in _nmf_kwargs.items()
                      if k != "n_jobs"},
                     solver_recipe=_seq_recipe.label))

            def _solve_seq(k_r, seed_r):
                kwargs = dict(_nmf_kwargs)
                kwargs["random_state"] = int(seed_r)
                kwargs["n_components"] = int(k_r)
                # pin the RECORDED recipe — run_nmf must not re-resolve
                # from env at solve time, or a knob mutation between the
                # dispatch event above and this task would desync
                # provenance from the engaged math
                kwargs["recipe"] = _seq_recipe
                spectra, _usages, err = self._nmf(norm_counts.X, kwargs)
                return np.asarray(spectra), err

            for idx in jobs:
                p = run_params.iloc[idx, :]
                print("[Worker %d]. Starting task %d." % (worker_i, idx))
                if heartbeat is not None:
                    heartbeat.beat(phase="task", cursor=idx)
                faults.maybe_straggle(context="factorize", worker=worker_i)
                k_t, it_t = int(p["n_components"]), int(p["iter"])
                spectra, err = _solve_seq(k_t, p["nmf_seed"])
                sp3, errs = faults.maybe_poison_lanes(
                    k_t, [it_t], spectra[None], np.asarray([err]),
                    seeds=[int(p["nmf_seed"])])
                healthy = guard.observe(
                    k_t, [it_t], [int(p["nmf_seed"])],
                    resilience.lane_health(errs, spectra=sp3))
                if healthy[0]:
                    self._write_iter_spectra(k_t, it_t, sp3[0],
                                             norm_counts.var.index)
                faults.maybe_kill("factorize", worker_i)

            def rerun_seq(k_r, seeds_r, iters=None, attempt=0):
                outs = [_solve_seq(k_r, s) for s in seeds_r]
                return (np.stack([o[0] for o in outs]),
                        np.asarray([o[1] for o in outs], np.float64))

            self._finish_resilience(guard, rerun_seq, norm_counts.var.index,
                                    worker_i)
            return

        if mesh is None:
            from ..parallel import default_mesh

            mesh = default_mesh()

        import jax
        import jax.numpy as jnp

        # sparsity-aware beta != 2 dispatch (ISSUE 1, ops/sparse.py): a
        # sparse norm_counts with a KL/IS ledger below the ELL density
        # threshold stays in its fixed-width ELL encoding — the sweeps then
        # run the nonzero-only kernels. Auto below the threshold;
        # CNMF_TPU_SPARSE_BETA=0 forces dense, =1 forces ELL. The dense
        # path remains the default everywhere else.
        beta_val = beta_loss_to_float(_nmf_kwargs["beta_loss"])
        # measured microbenches: the rho cost-ratio cache (ISSUE 11 —
        # no-op unless the accel knobs explicitly engage an amu schedule
        # for this beta) and the plan-point tuner (ISSUE 17 — measures
        # only under CNMF_TPU_AUTOTUNE=1/force; the auto default consumes
        # an existing cache without ever paying the bench on a stock run,
        # so cold-machine dispatch stays deterministic). Both best-effort
        # by construction: any failure keeps the static heuristics.
        from ..utils.autotune import maybe_autotune_plan, maybe_autotune_rho

        maybe_autotune_rho(beta=beta_val)
        maybe_autotune_plan()

        if skip_completed_runs and jobs:
            # sweep-granular resume: a K with ANY incomplete replicate
            # reruns this worker's whole K group. The vmapped while_loop
            # steps every lane until the batch's slowest lane converges,
            # so a lane's result depends on batch composition — rerunning
            # only the missing lanes would be valid but not bit-identical
            # to the uninterrupted run. Whole-group reruns make
            # interrupted+resumed sweeps byte-for-byte reproducible
            # (kill-resume parity, tests/test_resilience.py) and cost
            # almost nothing: the batch runs to its slowest lane either
            # way, and the overwrites are atomic.
            ks_incomplete = {int(run_params.iloc[i]["n_components"])
                             for i in jobs}
            # quarantined lanes stay excluded even when their K is
            # rerun for other reasons: re-solving a deterministically
            # divergent lane would burn the whole retry ladder again on
            # every resume. (In this compound case — quarantine + torn
            # lane in one K — the rerun batch omits the quarantined
            # lane, so bit-parity with an uninterrupted run is waived
            # for that K; validity and determinism of the rerun hold.)
            expanded = [i for i in my_tasks
                        if int(run_params.iloc[i]["n_components"])
                        in ks_incomplete and i not in quarantined_idx]
            if len(expanded) > len(jobs):
                print("[Worker %d]. Resume reruns %d replicate(s) (whole-K "
                      "groups for K=%s) so resumed sweeps are bit-identical "
                      "to uninterrupted ones."
                      % (worker_i, len(expanded),
                         ",".join(str(k) for k in sorted(ks_incomplete))))
            jobs = expanded
        _credit_completed(jobs)

        by_k: dict[int, list] = {}
        for idx in jobs:
            p = run_params.iloc[idx, :]
            by_k.setdefault(int(p["n_components"]), []).append(
                (int(p["iter"]), int(p["nmf_seed"])))

        # the resolved EXECUTION PLAN (ISSUE 17, runtime/planner.py):
        # every dispatch decision for this factorize — encoding, solver
        # recipe, kernel, program shape, layout, streaming, ingest tier,
        # store backend — resolved in ONE call (delegating to the same
        # registered resolvers the lint gate pins), logged whole as one
        # `plan` telemetry event, and consumed below instead of
        # re-resolving per site. Precedence per field: explicit knob /
        # caller argument > autotuned microbench point > static heuristic.
        from ..runtime.planner import InputStats, build_plan

        _sparse_in = sp.issparse(norm_counts.X)
        density = ell_w = None
        if _sparse_in:
            from ..ops.sparse import ell_row_width

            n_c, g_c = norm_counts.X.shape
            ell_w = ell_row_width(norm_counts.X)
            density = norm_counts.X.nnz / max(n_c * g_c, 1)
        plan = build_plan(
            InputStats(
                n=int(norm_counts.X.shape[0]),
                g=int(norm_counts.X.shape[1]), beta=beta_val,
                mode=_nmf_kwargs.get("mode", "online"),
                init=_nmf_kwargs.get("init", "random"),
                algo=_nmf_kwargs.get("algo", "mu"),
                sparse=_sparse_in, density=density, ell_width=ell_w,
                k_max=max(by_k) if by_k else None, n_ks=len(by_k),
                max_replicates=max((len(t) for t in by_k.values()),
                                   default=0),
                total_workers=max(1, int(total_workers)),
                has_store=store is not None),
            overrides={"packed": packed, "layout": "1d",
                       "mesh_devices": (1 if mesh is None
                                        else int(np.prod(
                                            mesh.devices.shape))),
                       "ooc_engaged": store is not None})
        use_ell = plan.use_ell
        self._events.emit("plan", plan=plan.to_dict(),
                          signature=plan.signature())
        if _sparse_in and beta_val in (1.0, 0.0):
            # knob-level encoding record (pre-dates the plan event; kept
            # for report/test continuity — the plan event is authoritative)
            self._events.emit(
                "dispatch", decision="ell_vs_dense",
                context={"use_ell": bool(use_ell), "beta": float(beta_val),
                         "density": round(float(density), 4),
                         "ell_width": int(ell_w), "genes": int(g_c),
                         "kernel": plan.kernel})

        if use_ell and packed:
            # fail BEFORE the CSR->ELL conversion and host->HBM staging
            raise ValueError(
                "packed K-sweeps run dense only; set CNMF_TPU_SPARSE_BETA=0 "
                "to keep packed=True, or drop packed for the ELL path")

        if use_ell:
            from jax.sharding import NamedSharding, PartitionSpec

            from ..ops.sparse import (csr_to_ell, ell_chunk_rows,
                                      ell_device_put)

            if _nmf_kwargs.get("mode", "online") == "online":
                Xe, _ = ell_chunk_rows(
                    norm_counts.X,
                    int(min(_nmf_kwargs.get("online_chunk_size", 5000),
                            norm_counts.X.shape[0])))
            else:
                Xe = csr_to_ell(norm_counts.X)
            X = ell_device_put(
                Xe, None if mesh is None
                else NamedSharding(mesh, PartitionSpec()))
            print("factorize: ELL sparse path engaged for beta=%g "
                  "(density %.3f, width %d of %d genes; "
                  "CNMF_TPU_SPARSE_BETA=0 forces dense)."
                  % (beta_val, density, X.width, norm_counts.X.shape[1]))
        else:
            X = norm_counts.X
            if sp.issparse(X):
                # over-density-threshold sparse fallback: slab-streamed
                # staging (ISSUE 13 satellite) — CSR slabs densify on
                # device one block at a time, so peak host bytes stay
                # slab-sized; the old X.toarray() materialized the full
                # dense matrix on host before the upload
                from ..parallel.streaming import (StreamStats,
                                                  stream_to_device)

                dense_stats = StreamStats()
                X = stream_to_device(X, stats=dense_stats,
                                     events=self._events)
                self._events.emit_stream("factorize_stage_dense",
                                         dense_stats)
            else:
                # device-resident once, reused by every per-K sweep
                # program (a jit argument, so the host->HBM transfer
                # happens exactly once)
                X = jnp.asarray(np.asarray(X, dtype=np.float32))
            if mesh is not None:
                # replicate across the mesh here rather than per sweep
                # call (device-to-device; the host link is paid once)
                from jax.sharding import NamedSharding, PartitionSpec

                X = jax.device_put(X, NamedSharding(mesh, PartitionSpec()))
            elif self._stageable(norm_counts.X):
                # donate the residency to the consensus stage (same size
                # guard as _stage_dense — donating an over-budget matrix
                # would pin HBM the cache can never serve): its refits use
                # the same matrix, so an in-process factorize->consensus
                # run (launcher, k-selection) never re-crosses the host
                # link
                self._dev_cache["norm_counts"] = (
                    self._content_token(norm_counts.X), X)

        if self._events.enabled:
            from ..parallel.streaming import (_csr_transport, stream_depth,
                                              stream_threads)

            self._events.emit(
                "dispatch", decision="stream_config",
                context={"transport": _csr_transport(jax.local_devices()),
                         "threads": stream_threads(),
                         "depth": stream_depth()})

        # packed-vs-per-K program shape: resolved by the PLAN above (the
        # auto regime heuristic — many Ks x few replicates — now lives in
        # planner._auto_packed; an explicit `packed` argument rode in as
        # a pin override). Only the argument-validation raise stays here.
        if packed and _nmf_kwargs["init"] != "random":
            raise ValueError(
                "packed K-sweeps require init='random' (the nndsvd family's "
                "SVD base is K-truncated); rerun with packed=False / "
                "--per-k-programs")
        packed = plan.packed

        # the resolved per-loss online schedule (ops/nmf.py:
        # resolve_online_schedule) is an execution detail the ledger YAML
        # doesn't carry — record what will actually run
        _h_tol_eff, _n_passes_eff, _h_tol_start = resolve_online_schedule(
            beta_loss_to_float(_nmf_kwargs["beta_loss"]),
            _nmf_kwargs.get("online_h_tol"), _nmf_kwargs.get("n_passes"))
        # solver recipe (ISSUE 9, ops/recipe.py): WHICH convergence math
        # the sweeps run — resolved once by the plan (same resolve_recipe
        # call, same precedence), recorded whole in the dispatch event +
        # provenance, and threaded into every sweep/warm call so the AOT
        # warmer keys the exact programs the sweeps dispatch
        recipe = plan.solver_recipe()
        self._events.emit("dispatch", decision="solver_recipe",
                          context=recipe.as_context())
        # the ENGAGED kernel label (ISSUE 16) — recipe-gated in the plan,
        # so a sketch recipe (whose scatter keeps the jnp chain) records
        # ell-jnp even under CNMF_TPU_PALLAS=1
        _kern = plan.kernel
        self._save_factorize_provenance(
            "batched-packed" if packed else
            ("batched-ell" if use_ell else "batched"), worker_i,
            dict({k: v for k, v in _nmf_kwargs.items() if k != "n_jobs"},
                 online_h_tol=_h_tol_eff, n_passes=_n_passes_eff,
                 online_h_tol_start=_h_tol_start,
                 sparse_path=("ell" if use_ell else "dense"),
                 solver_recipe=recipe.label, kernel=_kern,
                 inner_repeats=int(recipe.inner_repeats),
                 kl_newton=bool(recipe.kl_newton),
                 plan_signature=plan.signature(),
                 mesh_devices=(1 if mesh is None
                               else int(np.prod(mesh.devices.shape)))))

        def rerun_batched(k_r, seeds_r, iters=None, attempt=0):
            # quarantine-retry solver for the batched paths: a fresh per-K
            # sweep over the staged X with the derived seeds (the packed
            # program's K_max padding is irrelevant for a retry — bit
            # parity with the original attempt is not a goal, a healthy
            # fresh draw is)
            spectra_r, _, errs_r = replicate_sweep(
                X, seeds_r, k_r,
                beta_loss=_nmf_kwargs["beta_loss"],
                init=_nmf_kwargs["init"],
                mode=_nmf_kwargs.get("mode", "online"),
                tol=_nmf_kwargs.get("tol", 1e-4),
                online_chunk_size=_nmf_kwargs.get("online_chunk_size", 5000),
                online_chunk_max_iter=_nmf_kwargs.get(
                    "online_chunk_max_iter", _DEFAULT_CHUNK_MAX_ITER),
                alpha_W=_nmf_kwargs.get("alpha_W", 0.0),
                l1_ratio_W=_nmf_kwargs.get("l1_ratio_W", 0.0),
                alpha_H=_nmf_kwargs.get("alpha_H", 0.0),
                l1_ratio_H=_nmf_kwargs.get("l1_ratio_H", 0.0),
                mesh=mesh, replicates_per_batch=replicates_per_batch,
                n_rows=int(norm_counts.X.shape[0]) if use_ell else None,
                recipe=recipe)
            return np.asarray(spectra_r), np.asarray(errs_r)

        if packed and by_k:
            from ..parallel import replicate_sweep_packed

            tasks = [(k, it, seed) for k in sorted(by_k)
                     for (it, seed) in by_k[k]]
            print("[Worker %d]. Running %d replicates (K=%s) as ONE packed "
                  "program at K_max=%d."
                  % (worker_i, len(tasks),
                     ",".join(str(k) for k in sorted(by_k)),
                     max(by_k)))
            def write_slice(task_idx, spectra, errs):
                # eager per-slice writes: a mid-sweep crash keeps every
                # completed slice's files (--skip-completed-runs resumes).
                # Slices are K-homogeneous (replicate_sweep_packed groups
                # by K), so one health pass grades the whole slice.
                k = tasks[task_idx[0]][0]
                iters = [tasks[ti][1] for ti in task_idx]
                seeds_sl = [tasks[ti][2] for ti in task_idx]
                spectra, errs = faults.maybe_poison_lanes(
                    k, iters, spectra, errs, seeds=seeds_sl)
                healthy = guard.observe(
                    k, iters, seeds_sl,
                    resilience.lane_health(errs, spectra=spectra))
                for j, ti in enumerate(task_idx):
                    if not healthy[j]:
                        continue
                    _k, it, _seed = tasks[ti]
                    # stored, not deflated: 900 per-replicate writes cost
                    # ~3.2 s of a 12.6 s warm factorize in zlib alone, for
                    # transient files combine deletes under --clean
                    self._write_iter_spectra(_k, it, spectra[j][:_k],
                                             norm_counts.var.index)
                if heartbeat is not None:
                    heartbeat.beat(phase="slice", cursor=task_idx[0])
                faults.maybe_kill("factorize", worker_i)

            self._perf_iters = {}
            _perf_t0 = time.perf_counter()
            replicate_sweep_packed(
                X, [t[0] for t in tasks], [t[2] for t in tasks],
                beta_loss=_nmf_kwargs["beta_loss"],
                mode=_nmf_kwargs.get("mode", "online"),
                tol=_nmf_kwargs.get("tol", 1e-4),
                online_chunk_size=_nmf_kwargs.get("online_chunk_size", 5000),
                online_chunk_max_iter=_nmf_kwargs.get(
                    "online_chunk_max_iter", _DEFAULT_CHUNK_MAX_ITER),
                alpha_W=_nmf_kwargs.get("alpha_W", 0.0),
                l1_ratio_W=_nmf_kwargs.get("l1_ratio_W", 0.0),
                alpha_H=_nmf_kwargs.get("alpha_H", 0.0),
                l1_ratio_H=_nmf_kwargs.get("l1_ratio_H", 0.0),
                mesh=mesh, replicates_per_batch=replicates_per_batch,
                on_slice=write_slice, recipe=recipe,
                telemetry_sink=lambda _idx, pay:
                    self._emit_replicates_event(pay))
            self._finish_resilience(guard, rerun_batched,
                                    norm_counts.var.index, worker_i)
            _perf_acc, self._perf_iters = self._perf_iters, None
            self._emit_perf_model(
                "factorize", plan.kernel, int(norm_counts.X.shape[0]),
                int(norm_counts.X.shape[1]), _perf_acc,
                time.perf_counter() - _perf_t0, beta=beta_val,
                ell_width=plan.ell_width, bf16_ratio=plan.bf16_ratio)
            return

        if len(by_k) > 1:
            # compile all per-K programs concurrently before sweeping: the
            # serial first-call compiles otherwise dominate a cold multi-K
            # run (parallel/replicates.py: warm_sweep_programs)
            from ..parallel import warm_sweep_programs

            # always the ORIGINAL (cells, genes): a pre-chunked EllMatrix's
            # leading dims are (n_chunks, chunk_rows), not cells
            n_progs = warm_sweep_programs(
                int(norm_counts.X.shape[0]), int(norm_counts.X.shape[1]),
                {k: len(t) for k, t in by_k.items()},
                beta_loss=_nmf_kwargs["beta_loss"],
                init=_nmf_kwargs["init"],
                mode=_nmf_kwargs.get("mode", "online"),
                tol=_nmf_kwargs.get("tol", 1e-4),
                online_chunk_size=_nmf_kwargs.get("online_chunk_size", 5000),
                online_chunk_max_iter=_nmf_kwargs.get(
                    "online_chunk_max_iter", _DEFAULT_CHUNK_MAX_ITER),
                alpha_W=_nmf_kwargs.get("alpha_W", 0.0),
                l1_ratio_W=_nmf_kwargs.get("l1_ratio_W", 0.0),
                alpha_H=_nmf_kwargs.get("alpha_H", 0.0),
                l1_ratio_H=_nmf_kwargs.get("l1_ratio_H", 0.0),
                mesh=mesh, replicates_per_batch=replicates_per_batch,
                ell_dims=(X.width, X.t_width) if use_ell else None,
                recipe=recipe)
            print("[Worker %d]. Warmed %d sweep programs concurrently."
                  % (worker_i, n_progs))

        # pipelined sweep: dispatch runs ahead of fetch+save by a bounded
        # window, so device->host copies of earlier Ks overlap the compute
        # of later ones while (a) each K's spectra files still land on disk
        # as soon as that K is done (crash-resume via --skip-completed-runs
        # keeps working) and (b) at most `window` Ks' results sit in HBM
        self._perf_iters = {}
        _perf_t0 = time.perf_counter()
        pending: list[tuple[int, list, list, object, object]] = []
        window = 4
        # sweep telemetry payloads hold DEVICE arrays until their K drains
        # — converting eagerly would block the dispatch-ahead window
        telem_by_k: dict[int, dict] = {}

        def _drain(count):
            while len(pending) > count:
                k, iters, seeds_k, spectra_d, errs_d = pending.pop(0)
                spectra = np.asarray(spectra_d)
                errs = np.asarray(errs_d)
                payload = telem_by_k.pop(k, None)
                spectra, errs = faults.maybe_poison_lanes(
                    k, iters, spectra, errs, seeds=seeds_k)
                # always-on health pass over the final objectives +
                # written spectra. Deliberately does NOT fold in the
                # telemetry nonfinite latch: quarantine decisions must be
                # identical with and without CNMF_TPU_TELEMETRY — an
                # observability flag must never change which spectra land
                # on disk. (A transiently-inf-then-recovered lane stays
                # visible in the latch's `fault`-free telemetry record.)
                healthy = guard.observe(
                    k, iters, seeds_k,
                    resilience.lane_health(errs, spectra=spectra))
                for r, it in enumerate(iters):
                    if not healthy[r]:
                        continue
                    self._write_iter_spectra(k, it, spectra[r],
                                             norm_counts.var.index)
                self._emit_replicates_event(payload)
                faults.maybe_kill("factorize", worker_i)

        for k, tasks in sorted(by_k.items()):
            iters = [t[0] for t in tasks]
            seeds = [t[1] for t in tasks]
            print("[Worker %d]. Running %d replicates for k=%d as one "
                  "batched program." % (worker_i, len(tasks), k))
            if heartbeat is not None:
                heartbeat.beat(phase="sweep", cursor=k)
            faults.maybe_straggle(context="factorize", worker=worker_i)
            spectra_d, _, errs_d = replicate_sweep(
                X, seeds, k,
                beta_loss=_nmf_kwargs["beta_loss"],
                init=_nmf_kwargs["init"],
                mode=_nmf_kwargs.get("mode", "online"),
                tol=_nmf_kwargs.get("tol", 1e-4),
                online_chunk_size=_nmf_kwargs.get("online_chunk_size", 5000),
                online_chunk_max_iter=_nmf_kwargs.get(
                    "online_chunk_max_iter", _DEFAULT_CHUNK_MAX_ITER),
                alpha_W=_nmf_kwargs.get("alpha_W", 0.0),
                l1_ratio_W=_nmf_kwargs.get("l1_ratio_W", 0.0),
                alpha_H=_nmf_kwargs.get("alpha_H", 0.0),
                l1_ratio_H=_nmf_kwargs.get("l1_ratio_H", 0.0),
                mesh=mesh, replicates_per_batch=replicates_per_batch,
                fetch=False, recipe=recipe,
                # pre-chunked ELL leaves carry padded rows; the sweep needs
                # the true cell count for the init scale + program keys
                n_rows=int(norm_counts.X.shape[0]) if use_ell else None,
                telemetry_sink=lambda pay, _k=k:
                    telem_by_k.__setitem__(_k, pay))
            pending.append((k, iters, seeds, spectra_d, errs_d))
            _drain(window - 1)
        _drain(0)
        self._finish_resilience(guard, rerun_batched, norm_counts.var.index,
                                worker_i)
        _perf_acc, self._perf_iters = self._perf_iters, None
        self._emit_perf_model(
            "factorize", plan.kernel, int(norm_counts.X.shape[0]),
            int(norm_counts.X.shape[1]), _perf_acc,
            time.perf_counter() - _perf_t0, beta=beta_val,
            ell_width=plan.ell_width, bf16_ratio=plan.bf16_ratio)

    def _save_factorize_provenance(self, engaged_path: str, worker_i,
                                   effective_params: dict):
        """Record what factorize ACTUALLY ran. The prepared ledger YAML
        describes intent; auto-rowshard can swap the solver family, so the
        run artifacts carry the engaged path + effective parameters too."""
        record = {"engaged_path": engaged_path,
                  "worker_index": int(worker_i),
                  "effective_params": effective_params}
        path = self.paths["factorize_provenance"] % int(worker_i)
        from ..utils.anndata_lite import atomic_artifact

        with atomic_artifact(path) as tmp:  # never a half-written record
            with open(tmp, "w") as f:
                yaml.dump(record, f)
        # the engaged solver family + effective params IS the dispatch
        # decision — every factorize path funnels through here
        self._events.emit("dispatch", decision="solver_path",
                          context=dict({"engaged_path": engaged_path},
                                       **effective_params))

    def _emit_replicates_event(self, payload):
        """Land one sweep's convergence telemetry
        (``parallel.replicates._sweep_telemetry_payload``) as a
        ``replicates`` event. Array values may still be device arrays —
        converted here, at drain time, so the sweep pipeline's
        dispatch-ahead window is preserved."""
        if payload is None or not self._events.enabled:
            return
        from ..utils.telemetry import replicate_records

        records = replicate_records(payload)
        self._events.emit("replicates", k=payload["k"], beta=payload["beta"],
                          mode=payload["mode"], cap=int(payload["cap"]),
                          cadence=payload["cadence"],
                          recipe=payload.get("recipe"),
                          kernel=payload.get("kernel"),
                          records=records)
        # roofline accounting (ISSUE 19): while a factorize path has an
        # open accumulator, total the solver iterations per K — the pass
        # multiplicity its perf_model event scales the per-iteration
        # analytic cost by
        acc = getattr(self, "_perf_iters", None)
        if acc is not None:
            k = int(payload["k"])
            acc[k] = acc.get(k, 0) + sum(
                int(r.get("iters", 0)) for r in records)

    def _emit_perf_model(self, stage, lane, n, g, iters_by_k, wall_s,
                         *, beta, ell_width=None, bf16_ratio=False,
                         grid_shape=None, grid_blocks=None):
        """Join the analytic per-lane cost prediction
        (obs/costmodel.py, instantiated from the resolved plan's lane)
        with a measured wall as ONE schema-valid ``perf_model`` event:
        achieved MFU, achieved bandwidth fraction, and the compute- vs
        memory-bound roofline verdict. Host-side accounting only — off
        unless telemetry AND CNMF_TPU_PERF_MODEL are both on, and never
        takes factorize down."""
        from ..obs.costmodel import (chip_peaks, lane_cost,
                                     perf_model_enabled, roofline)

        if not (self._events.enabled and perf_model_enabled()):
            return
        if not iters_by_k or wall_s is None:
            return
        try:
            import jax

            kind = jax.devices()[0].device_kind
            backend = jax.default_backend()
        except Exception:
            kind, backend = None, "unknown"
        peaks = chip_peaks(kind)
        tot_f = tot_b = tot_coll = 0.0
        passes = 0
        exempt = backend != "tpu"
        for k, n_iters in sorted(iters_by_k.items()):
            c = lane_cost(lane, n, g, int(k), beta=beta,
                          ell_width=ell_width, bf16_ratio=bf16_ratio,
                          grid_shape=grid_shape, grid_blocks=grid_blocks)
            exempt = exempt or bool(c.get("perf_exempt"))
            tot_f += c["flops"] * int(n_iters)
            tot_b += c["bytes"] * int(n_iters)
            tot_coll += float(c.get("collective_bytes", 0.0)) * int(n_iters)
            passes += int(n_iters)
        roof = roofline(tot_f, tot_b, wall_s, peaks, perf_exempt=exempt)
        pred = {"flops": tot_f, "bytes": tot_b,
                "by_k": {str(k): int(v)
                         for k, v in sorted(iters_by_k.items())}}
        if tot_coll:
            pred["collective_bytes"] = tot_coll
        self._events.emit("perf_model", stage=stage, lane=lane,
                          predicted=pred,
                          measured={"wall_s": round(float(wall_s), 4),
                                    "passes": passes},
                          roofline=roof)

    def _write_iter_spectra(self, k, it, spectrum, columns):
        """One replicate's spectra artifact (atomic via save_df_to_npz);
        stored, not deflated — see the packed write path's note."""
        df = pd.DataFrame(spectrum, index=np.arange(1, int(k) + 1),
                          columns=columns)
        save_df_to_npz(df, self.paths["iter_spectra"] % (int(k), int(it)),
                       compress=False)

    def _finish_resilience(self, guard, rerun, columns, worker_i=0):
        """Retry waves + final accounting for one factorize call.

        ``rerun(k, seeds, iters=, attempt=) -> (spectra (R,k',g) numpy,
        errs (R,) numpy)`` re-solves a list of replicates at one K (each
        path supplies its own solver family; ``k' >= k`` for K_max-padded
        outputs; ``iters``/``attempt`` carry the lanes' ledger identity so
        the rowsharded path can checkpoint retries too). Seeds are derived
        per attempt (``resilience.derive_retry_seed``), so an interrupted
        run resumed later retries with identical seeds; the guard's ledger
        records every (seed, attempt, derived_seed, outcome) and the final
        quarantine set, then enforces the per-K min-healthy-frac floor."""
        from ..runtime import faults, resilience

        attempt = 1
        while attempt <= guard.max_retries:
            wave = guard.take_pending()
            if not wave:
                break
            by_k: dict[int, list] = {}
            for t in wave:
                by_k.setdefault(int(t["k"]), []).append(t)
            for k, tasks in sorted(by_k.items()):
                iters = [t["iter"] for t in tasks]
                orig_seeds = [t["seed"] for t in tasks]
                derived = [resilience.derive_retry_seed(s, attempt)
                           for s in orig_seeds]
                print("[Worker %d]. Retrying %d unhealthy replicate(s) for "
                      "k=%d with derived seeds (attempt %d/%d)."
                      % (worker_i, len(tasks), k, attempt,
                         guard.max_retries))
                spectra, errs = rerun(k, derived, iters=iters,
                                      attempt=attempt)
                spectra, errs = faults.maybe_poison_lanes(
                    k, iters, spectra, errs, attempt=attempt,
                    seeds=orig_seeds)
                healthy = guard.observe(
                    k, iters, orig_seeds,
                    resilience.lane_health(errs, spectra=spectra),
                    attempt=attempt, derived_seeds=derived)
                for j, it in enumerate(iters):
                    if healthy[j]:
                        self._write_iter_spectra(k, it, spectra[j][:k],
                                                 columns)
            attempt += 1
        guard.finalize()

    def _factorize_rowsharded(self, jobs, run_params, norm_counts,
                              nmf_kwargs, mesh, worker_i, guard=None,
                              resume=False, heartbeat=None, store=None,
                              grid=False):
        """Atlas-scale factorize: cells sharded over the mesh, replicates
        sequential. X streams host→HBM once (shard-sized CSR blocks, no host
        dense copy) and is reused by every replicate; padded rows contribute
        nothing to the psum'd W statistics (rowshard.py).

        Mid-run checkpointing (ISSUE 6, ``runtime/checkpoint.py``): under
        ``CNMF_TPU_CKPT_EVERY_PASSES`` (default 1) each replicate's pass
        state persists atomically per pass, and a ``resume``
        (``--skip-completed-runs``) continues an interrupted replicate
        from its newest valid checkpoint instead of re-deriving from
        scratch; ``=0`` keeps the fused pre-checkpoint programs,
        byte-identical. Shard staging failures flow into the resilience
        ledger (``ReplicateGuard.record_shard_fault``) before the run
        aborts cleanly.

        ``grid=True`` (ISSUE 13): the same execution shell over the true
        2-D (cells x genes) grid (``parallel/grid2d.py``) — X stages
        once sharded over BOTH axes, each replicate solves with
        axis-local compute-overlapped collectives, and every contract
        here (checkpoint resume, heartbeat liveness, hostloss re-mesh,
        resilience guard, telemetry) carries over unchanged."""
        from ..parallel import default_mesh
        from ..parallel.grid2d import (mesh_grid2d, nmf_fit_grid2d,
                                       stage_x_grid)
        from ..parallel.rowshard import nmf_fit_rowsharded, prepare_rowsharded

        if mesh is None and grid:
            mesh = mesh_grid2d()
        if mesh is None:
            mesh = default_mesh(axis_name="cells")
        if mesh is None:  # single device: a trivial 1-element mesh
            import jax
            from jax.sharding import Mesh

            mesh = Mesh(np.asarray(jax.devices()[:1]), ("cells",))

        from ..parallel.streaming import (ShardStallError, ShardUploadError,
                                          StreamStats)
        from ..utils.shardstore import RemoteStoreError, TornShardError
        from ..runtime import checkpoint as ckpt_mod
        from ..runtime import elastic, faults, resilience

        if guard is None:
            guard = resilience.ReplicateGuard(
                events=self._events,
                ledger_path=self.paths["resilience_ledger"] % int(worker_i))

        # liveness (ISSUE 8): under CNMF_TPU_HEARTBEAT_S this worker
        # stamps an atomic heartbeat (pass cursor included) at staging
        # and pass boundaries; the launcher's straggler containment and
        # barrier diagnoses read it back to name the culprit. Reuses the
        # caller's heartbeat when factorize() built one already.
        if heartbeat is None and elastic.heartbeat_s() > 0:
            heartbeat = elastic.Heartbeat(
                os.path.dirname(self.paths["resilience_ledger"]),
                self.name, int(worker_i), events=self._events)
        if heartbeat is not None:
            heartbeat.beat(phase="stage_x", force=True)
        import jax

        # in-process re-mesh is a single-controller recovery: on a
        # multi-host pod the surviving processes' collectives still span
        # the dead host (same constraint as the 2-D path), so the loss
        # propagates as the pre-elastic clean abort and the relaunch
        # minus the dead host resumes from checkpoints
        elastic_on = (elastic.elastic_enabled()
                      and jax.process_count() == 1)

        rs_beta = beta_loss_to_float(nmf_kwargs["beta_loss"])

        def _stage(mesh_):
            """Stage (or re-stage, after a degraded re-mesh) X onto
            ``mesh_`` through the streaming engine. Store-backed runs
            (ISSUE 10) stream slabs straight from disk — host residency
            bounded by the slab budget, staged array bit-identical to the
            in-memory path; a shard over the per-device resident budget
            skips staging entirely and returns the STORE, which
            ``nmf_fit_rowsharded`` runs as a slab-looped pass per solve."""
            stage_stats = StreamStats() if self._events.enabled else None
            try:
                if grid:
                    # grid staging: full-width row stripes split into
                    # per-device column tiles (store-backed inputs read
                    # only the slabs overlapping addressable stripes);
                    # no slab-loop tier — the grid's point is that the
                    # per-device TILE shrinks with BOTH axes
                    Xd_, _rp, _cp = stage_x_grid(
                        store if store is not None else norm_counts.X,
                        mesh_, stats=stage_stats, events=self._events,
                        liveness=heartbeat)
                    n_orig_ = int(norm_counts.X.shape[0])
                elif store is not None:
                    from ..parallel.rowshard import store_dispatch

                    # force_dense: this path stages dense like its
                    # in-memory twin (store-backed runs stay BIT-identical
                    # to in-memory runs on the same ledger), so the
                    # resident-budget decision is sized with dense bytes
                    _, slab_loop = store_dispatch(
                        store, mesh_, rs_beta,
                        init=nmf_kwargs.get("init", "random"),
                        force_dense=True)
                    if slab_loop:
                        print("[Worker %d]. Store-backed shard exceeds "
                              "the per-device resident budget — running "
                              "slab-looped out-of-core passes "
                              "(CNMF_TPU_OOC_SHARD_BYTES)." % worker_i)
                        return store, store.n_rows
                    Xd_, n_orig_ = prepare_rowsharded(
                        store, mesh_, stats=stage_stats,
                        events=self._events, liveness=heartbeat)
                else:
                    Xd_, n_orig_ = prepare_rowsharded(norm_counts.X, mesh_,
                                                      stats=stage_stats,
                                                      events=self._events,
                                                      liveness=heartbeat)
            except (ShardUploadError, ShardStallError,
                    TornShardError, RemoteStoreError) as exc:
                # exhausted/stalled shards, store slabs that failed
                # digest validation past the retry budget, and a remote
                # store down past the transport budget with no cached
                # copy all land in the PR-4 ledger before the abort: the
                # staged array cannot be completed, so there is no
                # degraded mode here — but the audit trail (and the
                # launcher's respawn, which re-stages) must see WHY the
                # worker died
                guard.record_shard_fault(
                    "shard_stall" if isinstance(exc, ShardStallError)
                    else ("shard_read_torn"
                          if isinstance(exc, TornShardError)
                          else ("remote_store"
                                if isinstance(exc, RemoteStoreError)
                                else "shard_upload_failed")),
                    {"stage": "rowshard_stage_x", "error": str(exc)})
                guard.finalize()
                raise
            if stage_stats is not None:
                self._events.emit_stream("rowshard_stage_x", stage_stats)
            return Xd_, n_orig_

        Xd, n_orig = _stage(mesh)
        # mesh/Xd live in a mutable cell: a degraded re-mesh mid-sweep
        # swaps both, and every later solve reads the current topology
        topo = {"mesh": mesh, "Xd": Xd}
        _, n_passes_eff, _ = resolve_online_schedule(
            beta_loss_to_float(nmf_kwargs["beta_loss"]), 0.05,
            nmf_kwargs.get("n_passes"))
        if grid:
            _gc, _gg = mesh.devices.shape
            print("[Worker %d]. 2-D grid factorize: %d cells x %d genes "
                  "over a %d x %d (cells x genes) grid, %d tasks."
                  % (worker_i, n_orig, int(norm_counts.X.shape[1]),
                     int(_gc), int(_gg), len(jobs)))
        else:
            print("[Worker %d]. Row-sharded factorize: %d cells over %d "
                  "devices, %d tasks." % (worker_i, n_orig,
                                          int(np.prod(mesh.devices.shape)),
                                          len(jobs)))
        # solver recipe for the sharded pass program (ISSUE 9): only the
        # dna lane applies here (the pass loop IS the amu repeat schedule
        # natively); resolved once, recorded in dispatch + provenance,
        # and pinned into the checkpoint identity below
        from ..ops.recipe import resolve_recipe as _resolve_recipe
        from ..ops.sparse import EllMatrix as _EllMatrix

        # algo pinned to 'mu': the sharded pass implements the MU family
        # only (the ledger's algo was already among its ignored keys).
        # A store handed back by _stage (the slab-looped deep tier) runs
        # the dense pass program — only an EllMatrix means ELL kernels.
        recipe = _resolve_recipe(
            rs_beta, "rowshard", algo="mu",
            ell=isinstance(Xd, _EllMatrix),
            n=int(norm_counts.X.shape[0]), g=int(norm_counts.X.shape[1]),
            k=max((int(run_params.iloc[i]["n_components"]) for i in jobs),
                  default=None))
        self._events.emit("dispatch", decision="solver_recipe",
                          context=recipe.as_context())
        # engaged inner-loop kernel (ISSUE 16): the fused Pallas kernels
        # run only on ELL β=1 shards (the grid2d layout stages dense
        # stripes, so its label is the literal dense chain); the label
        # rides the provenance record and, when the kernels engage, the
        # checkpoint identity below
        from ..ops.pallas import resolve_pallas as _resolve_pallas

        rs_use_pallas = bool(
            not grid and isinstance(Xd, _EllMatrix) and rs_beta == 1.0
            and recipe.algo != "sketch" and _resolve_pallas())
        rs_kernel = ("dense-jnp" if grid or not isinstance(Xd, _EllMatrix)
                     else ("ell-pallas" if rs_use_pallas else "ell-jnp"))
        from ..parallel.grid2d import grid_blocks as _grid_blocks
        from ..parallel.grid2d import grid_overlap_enabled as _grid_ovl

        grid_ctx = {}
        if grid:
            _gc, _gg = (int(d) for d in mesh.devices.shape)
            grid_ctx = {"mesh_shape": [_gc, _gg],
                        "overlap": bool(_grid_ovl()),
                        "blocks": [
                            _grid_blocks(int(Xd.shape[1]) // _gg),
                            _grid_blocks(int(Xd.shape[0]) // _gc)]}
        # the row-sharded block-coordinate solver ignores the ledger's
        # mode/batch_max_iter/online_chunk_size; record what actually runs
        self._save_factorize_provenance(
            "grid2d" if grid else "rowshard", worker_i,
            dict(grid_ctx) |
            {"beta_loss": nmf_kwargs["beta_loss"],
             "init": nmf_kwargs.get("init", "random"),
             "tol": nmf_kwargs.get("tol", 1e-4),
             "n_passes": n_passes_eff,
             "chunk_max_iter": nmf_kwargs.get("online_chunk_max_iter", _DEFAULT_CHUNK_MAX_ITER),
             "alpha_W": nmf_kwargs.get("alpha_W", 0.0),
             "alpha_H": nmf_kwargs.get("alpha_H", 0.0),
             "solver_recipe": recipe.label,
             "kernel": rs_kernel,
             "kl_newton": bool(recipe.kl_newton),
             "mesh_devices": int(np.prod(mesh.devices.shape)),
             "ooc_ingest": (None if store is None else
                            ("slab_loop" if not isinstance(
                                Xd, (jax.Array, _EllMatrix))
                             else "store_resident")),
             "ledger_keys_ignored": ["mode", "online_chunk_size"]})

        if grid and self._events.enabled and jobs:
            # measured collective probe (ISSUE 13): time one pass with
            # the double-buffered overlap vs the serializing barrier vs
            # a collectives-only program, and put the hidden-collective
            # fraction on the record next to the per-solve collective
            # events. Observability only — never takes factorize down.
            from ..parallel.grid2d import measure_collectives
            try:
                k_probe = int(run_params.iloc[jobs[0]]["n_components"])
                # observability-grade settings: 3 interleaved repeats
                # (the bench tier owns the high-repeat measurement), and
                # the PRODUCTION chunk_max_iter so the overlap=True pass
                # program is the very executable the checkpointed loop
                # dispatches on unregularized runs (the default) — only
                # the serial variant and the tiny psum-probe are then
                # extra compiles
                probe = measure_collectives(
                    topo["Xd"], k_probe, mesh, beta=rs_beta,
                    chunk_max_iter=int(nmf_kwargs.get(
                        "online_chunk_max_iter", _DEFAULT_CHUNK_MAX_ITER)),
                    repeats=3)
                self._events.emit(
                    "collective",
                    context=dict(grid_ctx, stage="grid2d_probe",
                                 k=k_probe, beta=float(rs_beta),
                                 pass_overlap_s=probe["pass_overlap_s"],
                                 pass_serial_s=probe["pass_serial_s"],
                                 coll_chained_s=probe["coll_chained_s"],
                                 coll_free_s=probe["coll_free_s"],
                                 pass_hidden_fraction=probe[
                                     "pass_hidden_fraction"]),
                    wall_s=probe["coll_chained_s"],
                    nbytes=probe["nbytes_per_pass"],
                    overlap_fraction=probe["overlap_fraction"])
            except Exception as exc:
                warnings.warn("grid2d collective probe failed (%s); "
                              "continuing without the overlap "
                              "measurement" % (exc,),
                              RuntimeWarning, stacklevel=2)

        # mid-run checkpoint policy: cadence from the env (0 disables —
        # the solver then compiles the exact pre-checkpoint fused
        # programs); the input digest pins a checkpoint to THIS matrix.
        # Store-backed runs pin the STORE digest instead (ISSUE 10): it
        # folds every slab's content digest, so a resume across a
        # re-prepare (new store) restarts instead of splicing two
        # matrices' trajectories — and the placeholder AnnData a
        # store-authoritative run carries is never hashed.
        ckpt_every = ckpt_mod.ckpt_every_passes()
        beta_val = rs_beta
        if ckpt_every <= 0:
            digest = None
        elif store is not None:
            digest = "store:" + store.store_digest
        else:
            digest = ckpt_mod.input_digest(norm_counts.X)
        # resolved-solver-recipe signature: pins the checkpoint to the
        # SETTINGS it was computed under, not just the matrix — a
        # re-prepare with different iteration caps/regularization, or a
        # knob flip that swaps the convergence math (plain MU vs the dna
        # Newton lane), must restart the replicate, never splice two
        # recipes' trajectories
        params_base = {
            "init": str(nmf_kwargs.get("init", "random")),
            "tol": float(nmf_kwargs.get("tol", 1e-4)),
            "n_passes": int(n_passes_eff),
            "chunk_max_iter": int(nmf_kwargs.get(
                "online_chunk_max_iter", _DEFAULT_CHUNK_MAX_ITER)),
            "alpha_W": float(nmf_kwargs.get("alpha_W", 0.0)),
            "l1_ratio_W": float(nmf_kwargs.get("l1_ratio_W", 0.0)),
            "alpha_H": float(nmf_kwargs.get("alpha_H", 0.0)),
            "l1_ratio_H": float(nmf_kwargs.get("l1_ratio_H", 0.0)),
            "recipe": recipe.signature(),
        }

        def _params_sig():
            """Identity signature including the ENGAGED ingest tier: the
            slab-looped pass is block-coordinate (group-wise H, online W
            flavor) while the resident pass solves each shard jointly —
            a respawn whose shard-budget decision flipped (a different
            CNMF_TPU_OOC_SHARD_BYTES, or the device-derived default
            moving with free memory) must RESTART the replicate, never
            splice one tier's trajectory into the other's algorithm.
            Read from the live topo cell so an elastic re-mesh that flips
            the tier invalidates the old cursor too."""
            tier = ("slab_loop"
                    if not isinstance(topo["Xd"], (jax.Array, _EllMatrix))
                    else "resident")
            # the engaged LAYOUT is identity too: the grid splits the
            # statistics contractions over the gene axis — resuming a
            # 1-D rowshard cursor under --mesh-grid2d (or vice versa)
            # would splice two solvers' trajectories
            params = dict(params_base, ingest_tier=tier,
                          layout=("grid2d" if grid else "rowshard"),
                          # the ENCODING is identity too (ISSUE 17, the
                          # plan's math-affecting fragment): an ELL vs
                          # dense flip — e.g. an autotuned density
                          # crossover moving across runs — changes the
                          # statistics accumulation structure, so a
                          # resume across it restarts, never splices
                          encoding=("ell" if isinstance(
                              topo["Xd"], _EllMatrix) else "dense"))
            if rs_use_pallas:
                # engaged-kernel identity (ISSUE 16): the fused kernels
                # change accumulation order vs the jnp chain, so a resume
                # across a CNMF_TPU_PALLAS flip restarts; default-path
                # signatures stay byte-identical to pre-Pallas builds
                params["recipe"] = recipe.signature(kernel=rs_kernel)
            return repr(sorted(params.items()))

        def _make_ckpt(k_c, it_c, seed_c, attempt=0, force_resume=False):
            """Checkpoint policy for one (k, iter) solve. Retry attempts
            (``attempt >= 1``) checkpoint too — exactly the lanes that
            just burned a multi-hour solve — under an attempt-suffixed
            path with the DERIVED seed in the identity, and always load
            with ``resume=True``: the retry ladder is deterministic
            (identical derived seeds on relaunch), so a matching
            checkpoint can only be this retry's own interrupted state.
            ``force_resume`` (elastic continuation after a host loss):
            load even on a fresh run — the checkpoint just written by
            THIS session's interrupted solve is the state to continue
            from, not stale history."""
            if ckpt_every <= 0:
                return None
            path = self.paths["pass_checkpoint"] % (int(k_c), int(it_c))
            if int(attempt) > 0:
                assert path.endswith(".npz")
                path = path[:-4] + ".a%d.npz" % int(attempt)
            elif not resume and not force_resume:
                # fresh runs void prior retry cursors along with the
                # base one (PassCheckpointer only discards its own path)
                import glob as _glob

                for stale in _glob.glob(path[:-4] + ".a*.npz"):
                    try:
                        os.unlink(stale)
                    except OSError:
                        pass
            return ckpt_mod.PassCheckpointer(
                path, ckpt_every,
                meta={"k": int(k_c), "iter": int(it_c), "seed": int(seed_c),
                      "attempt": int(attempt), "digest": digest,
                      "beta": float(beta_val), "params": _params_sig()},
                events=self._events, worker=worker_i,
                resume=(bool(resume or force_resume) if int(attempt) == 0
                        else True))

        def _solve_rowshard(k_r, seed_r, ckpt=None):
            common = dict(
                beta_loss=nmf_kwargs["beta_loss"],
                init=nmf_kwargs.get("init", "random"),
                seed=int(seed_r),
                tol=nmf_kwargs.get("tol", 1e-4),
                n_passes=n_passes_eff,
                chunk_max_iter=nmf_kwargs.get("online_chunk_max_iter", _DEFAULT_CHUNK_MAX_ITER),
                alpha_W=nmf_kwargs.get("alpha_W", 0.0),
                l1_ratio_W=nmf_kwargs.get("l1_ratio_W", 0.0),
                alpha_H=nmf_kwargs.get("alpha_H", 0.0),
                l1_ratio_H=nmf_kwargs.get("l1_ratio_H", 0.0),
                n_orig=n_orig,
                telemetry_sink=self._emit_replicates_event,
                checkpoint=ckpt, heartbeat=heartbeat, recipe=recipe,
                events=self._events)
            if grid:
                _H, spectra, err = nmf_fit_grid2d(
                    topo["Xd"], int(k_r), topo["mesh"],
                    g_orig=int(norm_counts.X.shape[1]), **common)
            else:
                _H, spectra, err = nmf_fit_rowsharded(
                    topo["Xd"], int(k_r), topo["mesh"],
                    store_slab_loop=not isinstance(
                        topo["Xd"], (jax.Array, _EllMatrix)),
                    **common)
            return np.asarray(spectra), err

        def _remesh_after_loss(exc):
            """Degraded re-mesh (ISSUE 8): re-plan the cells mesh over
            the surviving devices, free the doomed staged array, and
            re-stage X from the original input through the streaming
            engine. Raises ``DegradedMeshError`` (chained to the loss)
            when fewer than CNMF_TPU_MIN_DEVICES devices survive."""
            lost = elastic.resolve_lost_devices(exc, topo["mesh"])
            old_n = int(np.prod(topo["mesh"].devices.shape))
            guard.record_shard_fault(
                "host_loss",
                {"context": "rowshard",
                 "lost_devices": [int(d.id) for d in lost],
                 "error": str(exc)})
            new_mesh = elastic.plan_degraded_mesh(topo["mesh"], lost)
            warnings.warn(
                "host/device loss mid-factorize (%s); continuing "
                "degraded on %d of %d devices — in-flight replicates "
                "resume from their pass checkpoints"
                % (exc, int(np.prod(new_mesh.devices.shape)), old_n),
                RuntimeWarning, stacklevel=2)
            _delete_staged(topo["Xd"])
            topo["mesh"] = new_mesh
            topo["Xd"], _ = _stage(new_mesh)
            self._events.emit(
                "fault", kind="remesh",
                context={"context": "rowshard", "from_devices": old_n,
                         "to_devices": int(np.prod(new_mesh.devices.shape))})

        def _solve_elastic(k_r, it_r, seed_r, attempt=0):
            """One replicate solve that survives topology loss: on a
            detected host/device loss the mesh shrinks to the survivors,
            X re-stages, and the solve re-enters with ``resume=True`` so
            the just-written pass checkpoint continues mid-run (bit-exact
            state; a loss at the post-checkpoint replicate boundary
            completes bit-identically, a mid-pass loss finishes its
            remaining passes on the shrunk mesh within solver
            tolerance)."""
            force_resume = False
            while True:
                ckpt = _make_ckpt(k_r, it_r, seed_r, attempt=attempt,
                                  force_resume=force_resume)
                try:
                    spectra, err = _solve_rowshard(k_r, seed_r, ckpt=ckpt)
                    # injectable loss at the replicate boundary — after
                    # the final checkpoint, before the artifact write
                    faults.maybe_hostloss(context="replicate",
                                          worker=worker_i)
                    return spectra, err, ckpt
                except BaseException as exc:
                    if not (elastic_on and elastic.is_device_loss(exc)):
                        raise
                    _remesh_after_loss(exc)  # DegradedMeshError aborts
                    force_resume = True

        _perf_t0 = time.perf_counter()
        _perf_passes: dict[int, int] = {}
        for idx in jobs:
            p = run_params.iloc[idx, :]
            k, it = int(p["n_components"]), int(p["iter"])
            _perf_passes[k] = _perf_passes.get(k, 0) + int(n_passes_eff)
            faults.maybe_straggle(context="factorize", worker=worker_i)
            spectra, err, ckpt = _solve_elastic(k, it, p["nmf_seed"])
            sp3, errs = faults.maybe_poison_lanes(
                k, [it], spectra[None], np.asarray([err]),
                seeds=[int(p["nmf_seed"])])
            healthy = guard.observe(
                k, [it], [int(p["nmf_seed"])],
                resilience.lane_health(errs, spectra=sp3))
            if healthy[0]:
                self._write_iter_spectra(k, it, sp3[0],
                                         norm_counts.var.index)
            if ckpt is not None:
                # the replicate's durable artifact (or its quarantine
                # record, for unhealthy lanes whose retries run with
                # derived seeds) supersedes the mid-run cursor. Discarded
                # AFTER the artifact write: a kill in between still
                # resumes from the final checkpoint instead of rerunning
                ckpt.discard()
            faults.maybe_kill("factorize", worker_i)

        def rerun_rowshard(k_r, seeds_r, iters, attempt=0):
            # retries checkpoint too (review finding): these are exactly
            # the multi-hour replicates that just failed once — a
            # preemption mid-retry must not also lose the retry's passes,
            # and a host loss mid-retry re-meshes like the main loop
            outs = []
            for j, s in enumerate(seeds_r):
                spectra, err, ckpt = _solve_elastic(k_r, iters[j], s,
                                                    attempt=attempt)
                outs.append((spectra, err))
                if ckpt is not None:
                    ckpt.discard()
            return (np.stack([o[0] for o in outs]),
                    np.asarray([o[1] for o in outs], np.float64))

        self._finish_resilience(guard, rerun_rowshard, norm_counts.var.index,
                                worker_i)
        # roofline accounting (ISSUE 19): one pass of the sharded (or
        # 2-D grid) solver is the cost unit here, scaled by the
        # n_passes_eff each job ran
        self._emit_perf_model(
            "factorize_grid2d" if grid else "factorize_rowshard",
            "grid2d" if grid else rs_kernel,
            int(norm_counts.X.shape[0]), int(norm_counts.X.shape[1]),
            _perf_passes, time.perf_counter() - _perf_t0, beta=rs_beta,
            ell_width=(int(Xd.width) if isinstance(Xd, _EllMatrix)
                       else None),
            grid_shape=grid_ctx.get("mesh_shape"),
            grid_blocks=(max(grid_ctx["blocks"])
                         if grid_ctx.get("blocks") else None))

    def _factorize_2d(self, jobs, run_params, norm_counts, nmf_kwargs,
                      mesh, worker_i, replicates_per_batch=None,
                      store=None):
        """Factorize over the 2-D (replicates, cells) mesh — the multi-host
        layout (``parallel/multihost.py``): each replicate row-shards its
        cells over the mesh's cell axis (psum'd W statistics on ICI), the
        replicate axis spans hosts with zero solver traffic. X stages once,
        cells-sharded and replicate-axis-replicated, reused by every per-K
        sweep. On multi-host runs every process executes the same programs;
        only the coordinator writes artifacts (the reference's file
        dataplane, SURVEY.md §1.1, kept as the durable layer)."""
        import jax

        from ..parallel import is_coordinator, sync_hosts
        from ..parallel.multihost import replicate_sweep_2d, stage_x_2d
        from ..runtime import elastic

        # liveness (ISSUE 8): every mesh participant stamps a heartbeat
        # at stage/sweep boundaries; a barrier a dead host can never join
        # then raises a HostBarrierTimeout that NAMES the silent process
        heartbeat = None
        if elastic.heartbeat_s() > 0:
            heartbeat = elastic.Heartbeat(
                os.path.dirname(self.paths["resilience_ledger"]),
                self.name, int(jax.process_index()), events=self._events)
            heartbeat.beat(phase="stage_x_2d", force=True)
        elastic_on = elastic.elastic_enabled()

        # store-backed pods (ISSUE 10): each process streams ONLY the
        # store slabs overlapping its addressable cell shards from disk
        # — stage_x_2d's _shard_slices enumerates addressable devices, so
        # no process ever materializes the full matrix in host RAM
        x_src = store if store is not None else norm_counts.X
        Xd = stage_x_2d(x_src, mesh, events=self._events,
                        liveness=heartbeat)
        _, n_passes_eff, _ = resolve_online_schedule(
            beta_loss_to_float(nmf_kwargs["beta_loss"]), 0.05,
            nmf_kwargs.get("n_passes"))
        n_orig = int(norm_counts.X.shape[0])
        r_dim, c_dim = mesh.devices.shape
        print("[Worker %d]. 2-D factorize: %d cells x %d replicate shards "
              "(%d x %d mesh, %d processes), %d tasks."
              % (worker_i, n_orig, r_dim, r_dim, c_dim,
                 jax.process_count(), len(jobs)))
        if is_coordinator():
            self._save_factorize_provenance(
                "mesh2d", worker_i,
                {"beta_loss": nmf_kwargs["beta_loss"],
                 "init": nmf_kwargs.get("init", "random"),
                 "tol": nmf_kwargs.get("tol", 1e-4),
                 "n_passes": n_passes_eff,
                 "chunk_max_iter": nmf_kwargs.get(
                     "online_chunk_max_iter", _DEFAULT_CHUNK_MAX_ITER),
                 "alpha_W": nmf_kwargs.get("alpha_W", 0.0),
                 "l1_ratio_W": nmf_kwargs.get("l1_ratio_W", 0.0),
                 "alpha_H": nmf_kwargs.get("alpha_H", 0.0),
                 "l1_ratio_H": nmf_kwargs.get("l1_ratio_H", 0.0),
                 "mesh_shape": [int(r_dim), int(c_dim)],
                 "num_processes": int(jax.process_count()),
                 "ledger_keys_ignored": ["mode", "online_chunk_size"]})

        by_k: dict[int, list] = {}
        for idx in jobs:
            p = run_params.iloc[idx, :]
            by_k.setdefault(int(p["n_components"]), []).append(
                (int(p["iter"]), int(p["nmf_seed"])))

        for k, tasks in sorted(by_k.items()):
            iters = [t[0] for t in tasks]
            seeds = [t[1] for t in tasks]
            if heartbeat is not None:
                heartbeat.beat(phase="sweep2d", cursor=k, force=True)
            while True:
                try:
                    spectra, _errs = replicate_sweep_2d(
                        Xd, seeds, k, mesh,
                        beta_loss=nmf_kwargs["beta_loss"],
                        init=nmf_kwargs.get("init", "random"),
                        tol=nmf_kwargs.get("tol", 1e-4),
                        n_passes=n_passes_eff,
                        chunk_max_iter=nmf_kwargs.get("online_chunk_max_iter", _DEFAULT_CHUNK_MAX_ITER),
                        alpha_W=nmf_kwargs.get("alpha_W", 0.0),
                        l1_ratio_W=nmf_kwargs.get("l1_ratio_W", 0.0),
                        alpha_H=nmf_kwargs.get("alpha_H", 0.0),
                        l1_ratio_H=nmf_kwargs.get("l1_ratio_H", 0.0),
                        replicates_per_batch=replicates_per_batch)
                    break
                except BaseException as exc:
                    # degraded re-mesh (ISSUE 8), single-controller form:
                    # a lost device shrinks the (replicates x cells) mesh
                    # over the survivors (_balanced_rc re-plans the same
                    # way the original mesh was planned), X re-stages,
                    # and the K's sweep reruns whole — the 2-D path has
                    # no per-pass checkpoints, so its recovery unit is
                    # the sweep, its parity solver-tolerance. Multi-host
                    # pods cannot shrink in-process (the surviving
                    # processes' collectives still span the dead host):
                    # there the loss propagates as a clean abort and the
                    # operator relaunches minus the dead host.
                    if not (elastic_on and jax.process_count() == 1
                            and elastic.is_device_loss(exc)):
                        raise
                    lost = elastic.resolve_lost_devices(exc, mesh)
                    old_n = int(np.prod(mesh.devices.shape))
                    self._events.emit(
                        "fault", kind="host_loss",
                        context={"context": "sweep2d",
                                 "lost_devices": [int(d.id) for d in lost],
                                 "error": str(exc)})
                    mesh = elastic.plan_degraded_mesh(mesh, lost)
                    r_dim, c_dim = mesh.devices.shape
                    warnings.warn(
                        "host/device loss mid-sweep (%s); re-planned a "
                        "%d x %d mesh over %d of %d devices and rerunning "
                        "k=%d" % (exc, r_dim, c_dim,
                                  int(np.prod(mesh.devices.shape)), old_n,
                                  k),
                        RuntimeWarning, stacklevel=2)
                    _delete_staged(Xd)
                    Xd = stage_x_2d(x_src, mesh,
                                    events=self._events,
                                    liveness=heartbeat)
                    self._events.emit(
                        "fault", kind="remesh",
                        context={"context": "sweep2d",
                                 "from_devices": old_n,
                                 "to_devices":
                                     int(np.prod(mesh.devices.shape))})
            if is_coordinator():
                for r, it in enumerate(iters):
                    df = pd.DataFrame(spectra[r],
                                      index=np.arange(1, k + 1),
                                      columns=norm_counts.var.index)
                    save_df_to_npz(df, self.paths["iter_spectra"] % (k, it),
                                   compress=False)
        sync_hosts("factorize_2d", heartbeat=heartbeat)

    # ------------------------------------------------------------------
    # combine
    # ------------------------------------------------------------------

    @_timed("combine")
    def combine(self, components=None, skip_missing_files=False):
        if isinstance(components, int):
            ks = [components]
        elif components is None:
            run_params = load_df_from_npz(
                self.paths["nmf_replicate_parameters"])
            ks = sorted(set(run_params.n_components))
        else:
            ks = components
        for k in ks:
            self.combine_nmf(k, skip_missing_files=skip_missing_files)

    def combine_nmf(self, k, skip_missing_files=False):
        """Stack per-iter spectra into the merged (n_iter*k x genes) matrix
        with ``iter%d_topic%d`` row labels (``cnmf.py:895-920``); tolerates
        dead-worker gaps when ``skip_missing_files``.

        Every loaded file is VALIDATED (loadable zip, k x n_genes shape,
        finite values — ``runtime.resilience.load_spectra_checked``): a
        torn npz from a killed pre-atomic-write worker, or any corrupt
        copy, is treated exactly like a missing file under
        ``skip_missing_files`` (warn + skip) instead of crashing
        mid-combine; without the flag it raises with the reason up front.
        Replicates the factorize guard QUARANTINED (resilience ledgers)
        are deliberately absent and skip silently — no flag needed."""
        import concurrent.futures
        import errno

        from ..runtime import resilience

        run_params = load_df_from_npz(self.paths["nmf_replicate_parameters"])
        print("Combining factorizations for k=%d." % k)
        subset = run_params[run_params.n_components == k].sort_values("iter")

        quarantined = resilience.load_quarantined_tasks(
            self.paths["resilience_ledger"])
        n_genes = None
        try:
            with open(self.paths["nmf_genes_list"]) as f:
                n_genes = len([ln for ln in f.read().split("\n") if ln])
        except OSError:
            pass  # factorize-only dirs may lack the genes list; shape-only

        def load_one(it):
            fn = self.paths["iter_spectra"] % (k, it)
            # quarantine records can outlive the run that wrote them
            # (worker-count changes leave other workers' ledgers behind):
            # a record only suppresses the missing/invalid artifact it
            # explains — a VALID artifact from a later healthy re-run
            # always wins (one load doubles as that validation)
            quarantined_here = (int(k), int(it)) in quarantined
            if not os.path.exists(fn):
                if quarantined_here:
                    print("Skipping quarantined replicate k=%d iter=%d "
                          "(see the resilience ledger)." % (k, it))
                    return None
                if not skip_missing_files:
                    print("Missing file: %s, run with skip_missing=True to "
                          "override" % fn)
                    raise FileNotFoundError(errno.ENOENT,
                                            os.strerror(errno.ENOENT), fn)
                print("Missing file: %s. Skipping." % fn)
                return None
            try:
                spectra = resilience.load_spectra_checked(fn, k=int(k),
                                                          n_genes=n_genes)
            except resilience.TornArtifactError as exc:
                if quarantined_here:
                    print("Skipping quarantined replicate k=%d iter=%d "
                          "(see the resilience ledger)." % (k, it))
                    return None
                self._events.emit("fault", kind="torn_artifact",
                                  context={"path": fn, "reason": str(exc)})
                if not skip_missing_files:
                    raise resilience.TornArtifactError(
                        "%s — rerun `factorize --skip-completed-runs` to "
                        "regenerate it, or combine with "
                        "skip_missing_files=True to drop it" % exc) from exc
                print("Corrupt file: %s. Skipping. (%s)" % (fn, exc))
                return None
            spectra.index = ["iter%d_topic%d" % (it, t + 1)
                             for t in range(k)]
            return spectra

        # npz decompression releases the GIL; reading a K's ~100 replicate
        # files concurrently cuts combine wall ~3x (order preserved below)
        with concurrent.futures.ThreadPoolExecutor(8) as ex:
            loaded = list(ex.map(load_one,
                                 [int(p["iter"])
                                  for _, p in subset.iterrows()]))
        combined = [df for df in loaded if df is not None]
        if combined:
            combined = pd.concat(combined, axis=0)
            save_df_to_npz(combined, self.paths["merged_spectra"] % k)
            return combined
        print("No spectra found for k=%d" % k)
        return combined

    # ------------------------------------------------------------------
    # refits
    # ------------------------------------------------------------------

    def _solver_params(self) -> dict:
        """The run's persisted solver-parameter YAML — the ONE parse
        shared by the refits and the warmers (the serving tier reads the
        same file through ``serving/reference.py``, which is what makes
        its batched dispatch parameter-identical to these refits)."""
        with open(self.paths["nmf_run_parameters"]) as f:
            return yaml.load(f, Loader=yaml.FullLoader)

    def refit_usage(self, X, spectra, usage=None, k_pad=None):
        """Fixed-spectra usage refit via the jitted MU H-solver
        (``cnmf.py:923-976`` -> :func:`cnmf_torch_tpu.ops.nmf.fit_h`).
        The H-subproblem is convex, so the fixed-key random init gives a
        deterministic result where the reference's unseeded torch init did
        not.

        Documented divergence: the refit solves the run's ACTUAL beta
        subproblem. The reference maps beta_loss name->number here
        (cnmf.py:944-951) but its ``fit_H_online`` takes no beta parameter
        (cnmf.py:260-271) — its KL/IS consensus refits silently minimize
        the Frobenius objective instead. For beta=2 runs the two agree
        (oracle-tested, test_reference_parity.py); for KL/IS this refit is
        consistent with the factorization objective where the reference's
        is not.

        Above ``rowshard_threshold`` cells the refit runs row-sharded
        (:func:`~cnmf_torch_tpu.parallel.fit_h_rowsharded`): X streams
        host->HBM shard-wise with no host dense copy — the reference's
        ``X.toarray()`` at this boundary (cnmf.py:329-330) is the wall for
        atlas-scale consensus.

        ``usage``: a previous usage matrix for the same (X, spectra)
        pair warm-starts the solve as ``H_init`` (clamped at zero) —
        repeat projections then converge in a fraction of the inner
        iterations (the serving tier's per-tenant warm-start cache,
        ``serving/batcher.py``, rides exactly this hook)."""
        kwargs = self._solver_params()
        beta = beta_loss_to_float(kwargs["beta_loss"])
        if isinstance(X, pd.DataFrame):
            X = X.values
        if isinstance(spectra, pd.DataFrame):
            spectra = spectra.values
        if X.shape[0] >= self.rowshard_threshold and usage is None:
            # k_pad (the packed K-selection entry) applies to the in-core
            # fit_h path only: the row-sharded solver compiles per-K, so
            # atlas-scale K-selection keeps per-K refit executables
            from ..parallel import default_mesh, fit_h_rowsharded

            mesh = default_mesh(axis_name="cells")
            if mesh is None:
                import jax
                from jax.sharding import Mesh

                mesh = Mesh(np.asarray(jax.devices()[:1]), ("cells",))
            return fit_h_rowsharded(
                X, np.asarray(spectra), mesh, h_tol=0.05,
                chunk_max_iter=int(kwargs["online_chunk_max_iter"]),
                l1_reg_H=float(kwargs["l1_ratio_H"]), l2_reg_H=0.0,
                beta=beta)
        return fit_h(
            X, np.asarray(spectra),
            H_init=None if usage is None else np.asarray(usage),
            chunk_size=int(kwargs["online_chunk_size"]),
            chunk_max_iter=int(kwargs["online_chunk_max_iter"]),
            h_tol=0.05,
            l1_reg_H=float(kwargs["l1_ratio_H"]),
            l2_reg_H=0.0,
            beta=beta,
            k_pad=k_pad)

    def refit_spectra(self, X, usage):
        """Transpose trick (``cnmf.py:979-994``) below the rowshard
        threshold. Above it, the transpose trick is exactly what must NOT
        happen — its row chunks become (chunk x n_cells) dense buffers — so
        the W-subproblem is solved directly from k-sized sufficient
        statistics / streamed row blocks
        (:func:`~cnmf_torch_tpu.parallel.rowshard.refit_w_rowsharded`).

        The transpose is routed into the staged dispatch (ISSUE 12
        satellite — this call used to hand ``fit_h`` a transposed host
        view whose staging materialized a full transposed copy next to
        X, doubling peak host memory; the sparse dense-fallback was
        worse, densifying the (genes x cells) transpose ON HOST): a
        device-resident X transposes on device; a host sparse X either
        keeps the nonzero-only ELL path (one index-sized CSC->CSR
        conversion) or stages slab-wise through the streaming engine and
        transposes on device — the host never holds a dense copy; a host
        dense X pays at most the ONE explicit contiguous copy."""
        if X.shape[0] >= self.rowshard_threshold:
            from ..parallel import default_mesh
            from ..parallel.rowshard import refit_w_rowsharded

            kwargs = self._solver_params()
            return refit_w_rowsharded(
                X, np.asarray(usage),
                beta=beta_loss_to_float(kwargs["beta_loss"]),
                h_tol=0.05,
                max_iter=int(kwargs["online_chunk_max_iter"]),
                l1_reg_W=float(kwargs["l1_ratio_W"]),
                # row-shard the beta != 2 staged refit over all chips (the
                # beta=2 path is k-sized statistics; mesh is unused there)
                mesh=default_mesh(axis_name="cells"))
        import jax

        if isinstance(X, jax.Array):
            Xt = X.T  # device transpose: no host copy at all
        elif sp.issparse(X):
            from ..ops.sparse import ell_row_width, resolve_sparse_beta

            beta = beta_loss_to_float(self._solver_params()["beta_loss"])
            Xt = None
            if float(beta) in (1.0, 0.0):
                # only the KL/IS lanes can take the ELL path, and the
                # decision needs just density + transposed row width —
                # both readable from the FREE CSC view (ell_row_width
                # counts via getnnz, no conversion). The O(nnz)
                # transposed CSR is built only when ELL actually wins.
                Xt_view = X.T
                n_t, g_t = Xt_view.shape
                if resolve_sparse_beta(
                        beta, density=X.nnz / max(n_t * g_t, 1),
                        width=ell_row_width(Xt_view), g=g_t):
                    # fit_h keeps this on the nonzero-only ELL kernels —
                    # same dispatch decision it would have made on the
                    # transposed view, minus the view's conversion
                    # ambiguity
                    Xt = Xt_view.tocsr()
            if Xt is None:
                # dense fallback: stage the row-major original slab-wise
                # (the full dense matrix never exists on host) and
                # transpose on device
                from ..parallel.streaming import (StreamStats,
                                                  stream_to_device)

                stats = StreamStats()
                Xt = stream_to_device(X, stats=stats,
                                      events=self._events).T
                stats.record_to(self._timer, "refit_spectra.stage")
        else:
            Xt = np.ascontiguousarray(np.asarray(X).T)
        return self.refit_usage(Xt, np.asarray(usage).T).T

    def _warm_consensus_programs(self, R, k, n_hv, g_hv, n_neighbors,
                                 stats_only, norm_counts=None):
        """Warm every device program the consensus call will hit —
        CONCURRENTLY, by executing each once on dummy data — and stage the
        refit matrices to HBM in the same pool.

        On a tunneled TPU each executable's FIRST dispatch pays a ~2 s
        program-upload round trip regardless of compile caching (AOT
        ``lower().compile()`` does not move the executable to the device);
        running the programs once in parallel overlaps those uploads, the
        compiles (which release the GIL), and the X staging transfers, so
        the serial consensus path then runs at warm dispatch cost. Each
        distinct shape-set warms once per process; failures only cost the
        warm. Ones as dummy data keep the MU/k-means while_loops at their
        early exits."""
        import jax.numpy as jnp

        # the distance-bearing warms must match the width consensus
        # will actually dispatch at — under the sketch lane that is the
        # projection dim, not g_hv (ops/sketch.py)
        sk = resolve_consensus_sketch(int(R), int(g_hv))
        feat_w = sk.dim if sk.engaged else g_hv
        sig = (R, int(k), n_hv, g_hv, int(n_neighbors), bool(stats_only),
               bool(sk.engaged), int(feat_w))
        if sig in self._warmed:
            if norm_counts is not None:
                self._stage_dense("norm_counts", norm_counts.X)
            return
        self._warmed.add(sig)

        kw = self._solver_params()
        beta = beta_loss_to_float(kw["beta_loss"])
        cmi = int(kw.get("online_chunk_max_iter", _DEFAULT_CHUNK_MAX_ITER))
        csz = int(kw.get("online_chunk_size", 5000))
        l1H = float(kw.get("l1_ratio_H", 0.0))
        f32 = jnp.float32

        # warming goes THROUGH the public step functions, not the inner jit
        # kernels: the eager helper ops around them (pad/reshape chunking,
        # transpose, seeded init) are separate tiny executables that each
        # pay their own first-dispatch upload on a tunneled device
        def dummy_ones(shape):
            # one shared device allocation per shape across ALL concurrent
            # warm invocations (k_selection_plot warms every K at once)
            with self._warm_lock:
                arr = self._warm_dummies.get(shape)
                if arr is None:
                    arr = jnp.ones(shape, f32)
                    self._warm_dummies[shape] = arr
            return arr

        def run_fit_h(rows, width, kk, transposed=False):
            Xd = (dummy_ones((width, rows)).T if transposed
                  else dummy_ones((rows, width)))
            fit_h(Xd, np.ones((kk, width), np.float32), chunk_size=csz,
                  chunk_max_iter=cmi, h_tol=0.05, l1_reg_H=l1H,
                  l2_reg_H=0.0, beta=beta)

        ones_Rf = np.ones((R, feat_w), np.float32)
        jobs = [lambda: kmeans(ones_Rf, int(k), n_init=10, seed=1),
                lambda: run_fit_h(n_hv, g_hv, int(k))]
        if sk.engaged:
            jobs.append(
                lambda: project_rows(np.ones((R, g_hv), np.float32),
                                     sk.dim))
        if stats_only:
            jobs.append(lambda: silhouette_score(
                ones_Rf, np.zeros((R,), np.int32), int(k)))
        else:
            jobs.append(lambda: knn_local_density(ones_Rf, int(n_neighbors)))
            jobs.append(lambda: kmeans(ones_Rf, int(k), n_init=10, seed=1,
                                       mask=np.ones((R,), dtype=bool)))
            try:
                from ..utils.anndata_lite import peek_h5ad_shape

                n_t, g_t = peek_h5ad_shape(self.paths["tpm"])
                if g_t < self.rowshard_threshold:
                    # the transposed-TPM refit (refit_spectra)
                    jobs.append(lambda: run_fit_h(g_t, n_t, int(k),
                                                  transposed=True))
                if (n_t < self.rowshard_threshold
                        and n_t * g_t * 4 <= self._DEV_CACHE_BUDGET_BYTES):
                    def stage_tpm_and_warm_scale():
                        # pre-read + stage only what _stage_dense accepts,
                        # then warm the final-refit HVG column-scale
                        # program against the staged array (its ~2 s
                        # first-dispatch upload otherwise lands inside
                        # the serial final_refit stage)
                        import jax

                        arr = self._stage_dense(
                            "tpm", read_h5ad(self.paths["tpm"]).X)
                        if isinstance(arr, jax.Array):
                            from ..ops.stats import scale_hvg_columns_device

                            scale_hvg_columns_device(
                                arr, np.zeros(g_hv, np.int64),
                                np.ones(g_hv))

                    jobs.append(stage_tpm_and_warm_scale)
            except Exception:
                pass
        if norm_counts is not None:
            jobs.append(lambda: self._stage_dense("norm_counts",
                                                  norm_counts.X))

        from ..parallel.replicates import run_warm_jobs

        run_warm_jobs(jobs)

    def _warm_kselection_packed(self, R_max, K_max, n_hv, g_hv):
        """Warm the packed K-selection program set (kmeans / silhouette /
        usage-refit at the sweep's shared padded shapes) concurrently —
        the packed analog of :meth:`_warm_consensus_programs`, three
        executables instead of three per K."""
        sk = resolve_consensus_sketch(int(R_max), int(g_hv))
        feat_w = int(sk.dim if sk.engaged else g_hv)
        sig = ("kpacked", int(R_max), int(K_max), int(n_hv), int(g_hv),
               bool(sk.engaged), feat_w)
        if sig in self._warmed:
            return
        self._warmed.add(sig)

        import jax.numpy as jnp

        kw = self._solver_params()
        beta = beta_loss_to_float(kw["beta_loss"])
        cmi = int(kw.get("online_chunk_max_iter", _DEFAULT_CHUNK_MAX_ITER))
        csz = int(kw.get("online_chunk_size", 5000))
        l1H = float(kw.get("l1_ratio_H", 0.0))

        # packed kmeans/silhouette dispatch at the sketched width when
        # the sketch lane is on (consensus pads the PROJECTED spectra)
        ones_Rg = np.ones((int(R_max), feat_w), np.float32)

        def warm_kmeans():
            kmeans(ones_Rg, int(K_max), n_init=10, seed=1,
                   n_rows=int(R_max), k_pad=int(K_max))

        def warm_sil():
            silhouette_score(ones_Rg, np.zeros((int(R_max),), np.int32),
                             n_rows=int(R_max), k_pad=int(K_max))

        def warm_refit():
            # the (n_hv, g_hv) dummy goes through the SHARED _warm_dummies
            # cache (ADVICE r5 #3): concurrent warm paths then hold ONE
            # device allocation per shape instead of a fresh unbudgeted
            # ones-array next to the staged norm_counts copy
            shape = (int(n_hv), int(g_hv))
            with self._warm_lock:
                arr = self._warm_dummies.get(shape)
                if arr is None:
                    arr = jnp.ones(shape, jnp.float32)
                    self._warm_dummies[shape] = arr
            # kk < K_max exercises the padded-init gather ops too
            kk = max(1, int(K_max) - 1)
            fit_h(arr, np.ones((kk, int(g_hv)), np.float32), chunk_size=csz,
                  chunk_max_iter=cmi, h_tol=0.05, l1_reg_H=l1H,
                  l2_reg_H=0.0, beta=beta, k_pad=int(K_max))

        jobs = [warm_kmeans, warm_sil]
        if (n_hv < self.rowshard_threshold
                and int(n_hv) * int(g_hv) * 4 <= env_int(
                    "CNMF_TPU_WARM_DUMMY_BUDGET_BYTES", 2 << 30, lo=0)):
            # above the threshold refit_usage takes fit_h_rowsharded, which
            # compiles per-K (k_pad unsupported there) — warming this
            # executable would only pin a useless (n, g) dummy in HBM; the
            # bytes budget mirrors _warm_harmony_programs' cap so warm +
            # production peak HBM stays bounded on large in-core datasets
            jobs.append(warm_refit)

        from ..parallel.replicates import run_warm_jobs

        run_warm_jobs(jobs)

    # ------------------------------------------------------------------
    # consensus
    # ------------------------------------------------------------------

    @_timed("consensus")
    def _consensus_stream_store(self):
        """The shard store consensus/k-selection should STREAM from, or
        ``None``. Streaming engages only when the store is authoritative
        (a ``CNMF_TPU_OOC=1`` prepare skipped the h5ad copy): with the
        h5ad present the resident path reads it bit-identically without
        a slab loop, and with neither present ``_read_norm_counts``
        raises its usual diagnosis."""
        if os.path.exists(self.paths["normalized_counts"]):
            return None
        return self._probe_store()

    def _stream_blocks(self, store, chunk_size, stats=None,
                       f64_extra=False, peak_base=0):
        """Yield ``(lo, hi, dense f32 block)`` row blocks of the store,
        boundaries pinned to ``chunk_size`` multiples (the bit-identity
        contract of ``ops.nmf.fit_h_slabbed``) and block bytes sized so
        the consumer's live set stays under the
        ``CNMF_TPU_OOC_BUDGET_BYTES`` slab budget (floor: one chunk —
        the refit's irreducible unit). ``f64_extra`` (the K-selection
        error pass): the consumer additionally holds a float64 copy of
        the block (2x), so blocks shrink by that factor AND the copy is
        charged into the residency high-water mark — the budget the OOC
        smoke asserts against covers the TRUE live set, not just the
        f32 block. ``stats`` collects per-block walls/bytes and that
        peak."""
        import time as _time

        from ..utils.shardstore import host_matrix_bytes, ooc_budget_bytes
        from ..utils.storebackend import backend_counter_snapshot

        bk_before = backend_counter_snapshot(store)
        n, g = store.shape
        chunk_size = int(min(int(chunk_size), max(n, 1)))
        chunk_bytes = max(chunk_size * g * 4, 1)
        # live set per block, sized against the block's DENSE bytes D:
        # the raw slab read (CSR triplets run ~2D at single-cell
        # densities) + the f32 block (a copy on the CSR path) + the
        # consumer's f64 copy (2D) when charged — so D <= budget/3
        # plain, budget/6 with the f64 copy, keeping the true live set
        # under the budget with slack for vstack transients
        divisor = 6 if f64_extra else 3
        chunks_per = max(1, (ooc_budget_bytes() // divisor) // chunk_bytes)
        rows_per = chunks_per * chunk_size
        if stats is not None and peak_base > stats.host_peak_bytes:
            # the caller's pass-lifetime working set (usage-sized init
            # draws / accumulators) rides every block's live set
            stats.host_peak_bytes = int(peak_base)
        t_start = _time.perf_counter()
        for lo in range(0, n, rows_per):
            hi = min(lo + rows_per, n)
            t0 = _time.perf_counter()
            blk = store.row_block(lo, hi, events=self._events)
            raw = host_matrix_bytes(blk)
            if sp.issparse(blk):
                dense = blk.toarray().astype(np.float32, copy=False)
            else:
                dense = np.asarray(blk, np.float32)
            if stats is not None:
                stats.add(disk_s=_time.perf_counter() - t0,
                          disk_nbytes=raw, slabs=1, nbytes=dense.nbytes)
                peak = (int(peak_base) + raw
                        + dense.nbytes * (3 if f64_extra else 1))
                if peak > stats.host_peak_bytes:
                    stats.host_peak_bytes = peak
            del blk
            yield lo, hi, dense
        if stats is not None:
            stats.wall_s += _time.perf_counter() - t_start
            # remote-store transport counters (ISSUE 15) accrued by this
            # pass's slab reads ride the caller's stream event
            stats.fold_store_counters(bk_before,
                                      backend_counter_snapshot(store))

    def _refit_usage_streamed(self, store, spectra, collect=None,
                              context="consensus_stream"):
        """Fixed-spectra usage refit streamed from the shard store —
        ``refit_usage``'s budget-bounded twin (ISSUE 13): identical
        solver parameters, chunk partition, and default init, so the
        result is BIT-identical to the resident ``fit_h`` dispatch on
        the assembled matrix while host residency stays one block."""
        from ..ops.nmf import fit_h_slabbed
        from ..parallel.streaming import StreamStats

        kwargs = self._solver_params()
        beta = beta_loss_to_float(kwargs["beta_loss"])
        stats = StreamStats()
        chunk = int(kwargs["online_chunk_size"])
        # usage-sized pass-lifetime buffers (the H0 draw + the output
        # usages fit_h_slabbed fills) ride every block's live set
        usage_bytes = 2 * store.n_rows * int(np.asarray(spectra).shape[0]) * 4
        H = fit_h_slabbed(
            self._stream_blocks(store, chunk, stats=stats,
                                peak_base=usage_bytes),
            store.n_rows, np.asarray(spectra),
            chunk_size=chunk,
            chunk_max_iter=int(kwargs["online_chunk_max_iter"]),
            h_tol=0.05, l1_reg_H=float(kwargs["l1_ratio_H"]),
            l2_reg_H=0.0, beta=beta, collect=collect)
        self._events.emit_stream(context, stats)
        return H

    def _streamed_prediction_errors(self, store, spectra_by_k):
        """The K-selection error curve from ONE shared slab pass over
        the store (ISSUE 13): ``_frobenius_prediction_error`` needs only
        ``HᵀX``, ``HᵀH`` and ``‖X‖²``, so each block is read once and
        serves EVERY K — per-K usages solve block-wise (the same chunked
        program the resident refit runs) and fold straight into the
        f64 statistics before the buffer drops. Returns
        ``{k: prediction_error}``; no stage assembles cells x genes.

        Working set: the per-K init draws and statistics are
        USAGE-sized — O(n x Σk) host bytes, the same order as the
        rf_usages artifact consensus must materialize anyway, charged
        into the residency peak below; the budget bounds the
        cells x genes (genes-sized) buffers."""
        from ..ops.nmf import _fit_h_block, fit_h_default_init
        from ..parallel.streaming import StreamStats

        kwargs = self._solver_params()
        beta = beta_loss_to_float(kwargs["beta_loss"])
        n, g = store.shape
        chunk = int(min(int(kwargs["online_chunk_size"]), max(n, 1)))
        cmi = int(kwargs["online_chunk_max_iter"])
        l1 = float(kwargs["l1_ratio_H"])
        W32 = {kk: np.asarray(W, np.float32)
               for kk, W in spectra_by_k.items()}
        H0 = {kk: np.asarray(fit_h_default_init(n, W.shape[0]))
              for kk, W in W32.items()}
        HtX = {kk: np.zeros((W.shape[0], g), np.float64)
               for kk, W in W32.items()}
        HtH = {kk: np.zeros((W.shape[0], W.shape[0]), np.float64)
               for kk, W in W32.items()}
        x_sq = 0.0
        stats = StreamStats()
        # the usage-sized per-K working set (H0 draws + f64 statistics)
        # is live for the whole pass — charged on top of every block's
        # genes-sized live set
        usage_bytes = sum(H0[kk].nbytes + HtX[kk].nbytes + HtH[kk].nbytes
                          for kk in H0)
        for lo, hi, Xb in self._stream_blocks(store, chunk, stats=stats,
                                              f64_extra=True,
                                              peak_base=usage_bytes):
            # ONE f64 copy of the block serves every K's HtX (numpy
            # would make the same upcast copy inside each mixed-dtype
            # matmul otherwise); it is charged to the residency peak and
            # the block sizing by _stream_blocks(f64_extra=True).
            # np.vdot accumulates the square sum without another temp.
            Xb64 = Xb.astype(np.float64)
            x_sq += float(np.vdot(Xb64, Xb64))
            for kk, W in W32.items():
                Hb = _fit_h_block(Xb, H0[kk][lo:hi], W, beta, chunk,
                                  cmi, 0.05, l1, 0.0).astype(np.float64)
                HtX[kk] += Hb.T @ Xb64
                HtH[kk] += Hb.T @ Hb
        self._events.emit_stream("kselection_stream", stats)
        out = {}
        for kk, W in spectra_by_k.items():
            W64 = np.asarray(W, np.float64)
            cross = float(np.sum(HtX[kk] * W64))
            hw_sq = float(np.sum((HtH[kk] @ W64) * W64))
            out[kk] = max(x_sq - 2.0 * cross + hw_sq, 0.0)
        return out

    def consensus(self, k, density_threshold=0.5,
                  local_neighborhood_size=0.30, show_clustering=True,
                  build_ref=True, skip_density_and_return_after_stats=False,
                  close_clustergram_fig=False, refit_usage=True,
                  normalize_tpm_spectra=False, norm_counts=None,
                  ols_batch_size=65536, _packed_dims=None,
                  _sketch_override=None, _stream_store=None,
                  _stream_error_collector=None):
        """Consensus spectra/usages from the merged replicate matrix
        (``cnmf.py:997-1256``): L2-normalize, KNN local-density outlier
        filter (cached), k-means(k, 10 inits, fixed key), cluster medians,
        usage refits, TPM- and z-score-unit spectra, artifacts + clustergram.

        ``_packed_dims`` ((R_max, K_max), stats-only runs): route the
        k-means / silhouette / usage-refit dispatches through the packed
        K-selection programs compiled once at the sweep's padded shapes —
        ``k_selection_plot`` passes this so its 9 Ks share 3 executables
        instead of paying ~3 first-dispatch uploads each (see
        ops/kmeans.py:_kmeans_packed_jit for the padding parity argument).
        """
        merged_spectra = load_df_from_npz(self.paths["merged_spectra"] % k)
        if _packed_dims is not None and not (
                skip_density_and_return_after_stats
                and merged_spectra.shape[0] <= _packed_dims[0]
                and int(k) <= _packed_dims[1]):
            _packed_dims = None  # partial-run ledger over-estimate: fall back
        store = _stream_store
        if norm_counts is None:
            if store is None:
                store = self._consensus_stream_store()
            if store is not None:
                # streaming consensus (ISSUE 13): under a store-
                # authoritative prepare (CNMF_TPU_OOC=1, h5ad skipped)
                # the usage refit and the error curve run as budget-
                # bounded slab loops over the store — no stage assembles
                # cells x genes on host. The AnnData view carries
                # metadata only (obs/var names, shape).
                norm_counts = self._store_anndata(store)
            else:
                norm_counts = self._read_norm_counts()

        density_threshold_str = str(density_threshold)
        if skip_density_and_return_after_stats:
            density_threshold_str = "2"
        density_threshold_repl = density_threshold_str.replace(".", "_")
        n_neighbors = int(local_neighborhood_size
                          * merged_spectra.shape[0] / k)

        if (env_flag("CNMF_WARM_CONSENSUS", True) and _packed_dims is None
                and store is None):
            # packed stats runs warm their (shared) program set in
            # k_selection_plot instead of a per-K set here; streaming
            # runs skip the warm outright — its dummy buffers are
            # dataset-sized, exactly what the slab budget forbids
            with self._timer.stage("consensus.warm"):
                self._warm_consensus_programs(
                    merged_spectra.shape[0], int(k), norm_counts.X.shape[0],
                    norm_counts.X.shape[1], n_neighbors,
                    skip_density_and_return_after_stats,
                    norm_counts=norm_counts)

        # L2-normalize rows (cnmf.py:1056)
        l2_spectra = (merged_spectra.T
                      / np.sqrt((merged_spectra ** 2).sum(axis=1))).T

        # sketched consensus (ISSUE 11, ops/sketch.py): the distance-
        # bearing stages (KNN density filter, k-means, silhouette,
        # clustergram distances) run on a seeded random projection of
        # the replicate spectra (~256 dims), turning the O(R^2 * g_hv)
        # reductions into O(R^2 * dim); cluster MEDIANS (the artifact)
        # are always recovered from the full-width spectra within the
        # final clusters, and the refits never see the projection
        # _sketch_override (k_selection_plot): the SWEEP-level decision,
        # resolved once from R_max — per-k auto resolution would compare
        # stats computed in different feature spaces across the Ks of
        # one selection curve (exact width below the engagement
        # threshold, projected above), biasing the selected K at the
        # boundary
        sk = (_sketch_override if _sketch_override is not None
              else resolve_consensus_sketch(int(l2_spectra.shape[0]),
                                            int(l2_spectra.shape[1])))
        cluster_feats = l2_spectra.values
        if sk.engaged:
            with self._timer.stage("consensus.sketch"):
                cluster_feats = project_rows(l2_spectra.values, sk.dim)
        self._events.emit(
            "dispatch", decision="consensus_path",
            context=dict(
                sk.as_context(),
                stage=("k_selection_stats"
                       if skip_density_and_return_after_stats
                       else "consensus"),
                k=int(k), replicates=int(l2_spectra.shape[0]),
                packed=_packed_dims is not None,
                distance_width=int(cluster_feats.shape[1]),
                distance_shape=[int(l2_spectra.shape[0])] * 2))

        topics_dist = None
        density_filter = None
        local_density = None
        kmeans_mask = None
        if not skip_density_and_return_after_stats:
            if (not sk.engaged
                    and os.path.isfile(
                        self.paths["local_density_cache"] % k)):
                local_density = load_df_from_npz(
                    self.paths["local_density_cache"] % k)
            else:
                with self._timer.stage("consensus.density"):
                    dens, topics_dist = knn_local_density(cluster_feats,
                                                          n_neighbors)
                local_density = pd.DataFrame(
                    dens, columns=["local_density"], index=l2_spectra.index)
                if not sk.engaged:
                    # sketched densities are JL-tolerance approximations;
                    # never write them into the exact runs' cache (and
                    # never read a cached exact pass as "the" sketched
                    # result — the parity gate compares both lanes)
                    save_df_to_npz(local_density,
                                   self.paths["local_density_cache"] % k)

            density_filter = local_density.iloc[:, 0] < density_threshold
            n_keep = int(density_filter.sum())
            if n_keep == 0:
                raise RuntimeError(
                    "Zero components remain after density filtering. "
                    "Consider increasing density threshold")
            if n_keep < k:
                # fewer surviving replicates than clusters: k-means can only
                # form n_keep distinct programs, so the output silently has
                # < k GEPs. (The reference crashes inside sklearn here;
                # warn-and-degrade keeps the two-pass threshold-tuning
                # workflow usable.)
                import warnings

                warnings.warn(
                    "density_threshold=%s keeps only %d of %d replicate "
                    "spectra — fewer than k=%d, so consensus will produce "
                    "only %d programs. Raise the threshold (run once with "
                    "2.0 and read the clustergram histogram)."
                    % (density_threshold, n_keep,
                       len(density_filter), k, n_keep),
                    UserWarning, stacklevel=2)
            if not density_filter.all():
                kmeans_mask = density_filter.values

        # masked k-means clusters the surviving subset at the FULL merged
        # matrix's static shape, so every density threshold in a tuning
        # sweep reuses one compiled program (no per-surviving-count
        # recompiles); the unfiltered paths keep the unmasked program
        l2_padded = None
        labels_padded = None
        with self._timer.stage("consensus.kmeans"):
            if _packed_dims is not None:
                R_actual = l2_spectra.shape[0]
                l2_padded = np.zeros((_packed_dims[0],
                                      cluster_feats.shape[1]), np.float32)
                l2_padded[:R_actual] = cluster_feats
                labels_padded, _centers, _inertia = kmeans(
                    l2_padded, int(k), n_init=10, seed=1, n_rows=R_actual,
                    k_pad=_packed_dims[1])
                labels_all = labels_padded[:R_actual]
            else:
                labels_all, _centers, _inertia = kmeans(cluster_feats, k,
                                                        n_init=10, seed=1,
                                                        mask=kmeans_mask)
        if kmeans_mask is not None:
            l2_spectra = l2_spectra.loc[density_filter, :]
            cluster_feats = cluster_feats[kmeans_mask]
            labels0 = labels_all[kmeans_mask]
        else:
            if density_filter is not None:
                l2_spectra = l2_spectra.loc[density_filter, :]
                cluster_feats = cluster_feats[density_filter.values]
            labels0 = labels_all
        kmeans_cluster_labels = pd.Series(labels0 + 1,
                                          index=l2_spectra.index)

        # cluster medians, renormalized to probability distributions
        # (cnmf.py:1087-1090)
        median_spectra = l2_spectra.groupby(kmeans_cluster_labels).median()
        median_spectra = (median_spectra.T / median_spectra.sum(axis=1)).T

        with self._timer.stage("consensus.refit_usage"):
            if store is not None:
                if skip_density_and_return_after_stats:
                    # stats mode: the usages are consumed ONLY by the
                    # prediction error, which the shared slab pass below
                    # computes fused with its own block solves — solving
                    # them here too would double the store reads
                    rf_usages = None
                else:
                    rf_usages = self._refit_usage_streamed(
                        store, median_spectra.values)
            else:
                X_resident = self._stage_dense("norm_counts",
                                               norm_counts.X)
                rf_usages = self.refit_usage(
                    X_resident, median_spectra,
                    k_pad=None if _packed_dims is None
                    else _packed_dims[1])
        if rf_usages is not None:
            rf_usages = pd.DataFrame(rf_usages,
                                     index=norm_counts.obs.index,
                                     columns=median_spectra.index)

        if skip_density_and_return_after_stats:
            if _packed_dims is not None:
                silhouette = silhouette_score(
                    l2_padded, labels_padded, n_rows=l2_spectra.shape[0],
                    k_pad=_packed_dims[1])
            else:
                # same feature space the clustering ran in (the sketched
                # stats path is where the quadratic cost lives)
                silhouette = silhouette_score(cluster_feats, labels0, k)
            if store is not None:
                if _stream_error_collector is not None:
                    # deferred to k_selection_plot's ONE shared slab
                    # pass over the store (every K's HᵀX/HᵀH/‖X‖²
                    # accumulate from the same block reads); the caller
                    # fills this K's cell afterwards
                    _stream_error_collector[int(k)] = \
                        median_spectra.values
                    prediction_error = float("nan")
                else:
                    prediction_error = self._streamed_prediction_errors(
                        store, {int(k): median_spectra.values})[int(k)]
            else:
                tok = self._content_token(norm_counts.X)
                if tok not in self._x_sq_cache:
                    self._x_sq_cache[tok] = _x_squared_sum(norm_counts.X)
                prediction_error = _frobenius_prediction_error(
                    norm_counts.X, rf_usages.values,
                    median_spectra.values, x_sq=self._x_sq_cache[tok])
            consensus_stats = pd.DataFrame(
                [k, density_threshold, silhouette, prediction_error],
                index=["k", "local_density_threshold", "silhouette",
                       "prediction_error"],
                columns=["stats"])
            return consensus_stats

        # re-order GEPs by total contribution (cnmf.py:1113-1120)
        norm_usages = rf_usages.div(rf_usages.sum(axis=1), axis=0)
        reorder = norm_usages.sum(axis=0).sort_values(ascending=False)
        rf_usages = rf_usages.loc[:, reorder.index]
        norm_usages = norm_usages.loc[:, reorder.index]
        median_spectra = median_spectra.loc[reorder.index, :]
        rf_usages.columns = np.arange(1, rf_usages.shape[1] + 1)
        norm_usages.columns = rf_usages.columns
        median_spectra.index = rf_usages.columns

        # TPM-unit spectra via the transposed refit (cnmf.py:1124-1129);
        # the staged TPM transposes on-device instead of a host CSC densify
        with self._timer.stage("consensus.refit_spectra"):
            tpm = read_h5ad(self.paths["tpm"])
            tpm_stats = load_df_from_npz(self.paths["tpm_stats"])
            tpm_resident = self._stage_dense("tpm", tpm.X)
            spectra_tpm = self.refit_spectra(
                tpm_resident, norm_usages.values.astype(np.float32))
        spectra_tpm = pd.DataFrame(spectra_tpm, index=rf_usages.columns,
                                   columns=tpm.var.index)
        if normalize_tpm_spectra:
            spectra_tpm = spectra_tpm.div(spectra_tpm.sum(axis=1),
                                          axis=0) * 1e6

        # z-score spectra: OLS of z-scored TPM against usages (cnmf.py:1132);
        # sparse TPM densifies one ols_batch_size row block at a time
        with self._timer.stage("consensus.ols"):
            usage_coef = ols_all_cols(rf_usages.values, tpm.X,
                                      normalize_y=True,
                                      batch_size=int(ols_batch_size))
        usage_coef = pd.DataFrame(usage_coef, index=rf_usages.columns,
                                  columns=tpm.var.index)

        if refit_usage:
            with self._timer.stage("consensus.final_refit"):
                # final usage refit on std-scaled HVG TPM (cnmf.py:1135-1149)
                hvgs = open(self.paths["nmf_genes_list"]).read().split("\n")
                spectra_tpm_rf = spectra_tpm.loc[:, hvgs]
                spectra_tpm_rf = spectra_tpm_rf.div(
                    tpm_stats.loc[hvgs, "__std"], axis=1)
                import jax

                if isinstance(tpm_resident, jax.Array):
                    # the TPM is already HBM-resident: slice + scale its HVG
                    # columns ON DEVICE (ops/stats.scale_hvg_columns_device) —
                    # host-scaling and re-uploading the dense result cost ~2 s
                    # per consensus call on a tunneled chip. The ddof=1 std is
                    # derived from the tpm_stats artifact (same f64 moment
                    # engine over the same matrix, ddof=0) instead of a fresh
                    # O(nnz) pass + HVG submatrix copy.
                    from ..ops.stats import scale_hvg_columns_device

                    n_rows = int(tpm_resident.shape[0])
                    bessel = (n_rows / (n_rows - 1.0)) if n_rows > 1 else 1.0
                    div = np.sqrt(
                        tpm_stats.loc[hvgs, "__std"].values.astype(np.float64)
                        ** 2 * bessel)
                    if sp.issparse(tpm.X):
                        div[div == 0] = 1.0
                    refit_X = scale_hvg_columns_device(
                        tpm_resident, tpm.var.index.get_indexer(hvgs), div)
                else:
                    norm_tpm = tpm[:, hvgs].copy()
                    if sp.issparse(norm_tpm.X):
                        norm_tpm.X, _ = scale_columns(norm_tpm.X, ddof=1,
                                                      zero_std_to_one=True)
                    else:
                        norm_tpm.X, _ = scale_columns(norm_tpm.X, ddof=1,
                                                      zero_std_to_one=False)
                    refit_X = norm_tpm.X
                rf_usages = self.refit_usage(
                    refit_X, spectra_tpm_rf.values.astype(np.float32))
                rf_usages = pd.DataFrame(rf_usages, index=norm_counts.obs.index,
                                         columns=spectra_tpm_rf.index)

        with self._timer.stage("consensus.writes"):
            save_df_to_npz(median_spectra, self.paths["consensus_spectra"]
                           % (k, density_threshold_repl))
            save_df_to_npz(rf_usages, self.paths["consensus_usages"]
                           % (k, density_threshold_repl))
            save_df_to_text(median_spectra, self.paths["consensus_spectra__txt"]
                            % (k, density_threshold_repl))
            save_df_to_text(rf_usages, self.paths["consensus_usages__txt"]
                            % (k, density_threshold_repl))
            save_df_to_npz(spectra_tpm, self.paths["gene_spectra_tpm"]
                           % (k, density_threshold_repl))
            save_df_to_text(spectra_tpm, self.paths["gene_spectra_tpm__txt"]
                            % (k, density_threshold_repl))
            save_df_to_npz(usage_coef, self.paths["gene_spectra_score"]
                           % (k, density_threshold_repl))
            save_df_to_text(usage_coef, self.paths["gene_spectra_score__txt"]
                            % (k, density_threshold_repl))

        if show_clustering:
            from .plots import clustergram

            if topics_dist is None:
                from ..ops import pairwise_euclidean

                # sketched runs plot JL-approximate distances (the
                # clustergram is a visualization; medians stay exact)
                topics_dist = pairwise_euclidean(cluster_feats)
            else:
                topics_dist = topics_dist[density_filter.values, :][
                    :, density_filter.values]
            clustergram(
                topics_dist, kmeans_cluster_labels, local_density,
                density_filter, density_threshold,
                self.paths["clustering_plot"] % (k, density_threshold_repl),
                close_fig=close_clustergram_fig)

        if build_ref:
            with self._timer.stage("consensus.build_ref"):
                self.build_reference(k, density_threshold,
                                     spectra_tpm=spectra_tpm)
        return None

    # ------------------------------------------------------------------
    # downstream artifacts
    # ------------------------------------------------------------------

    def build_reference(self, k, density_threshold=0.5, target_sum=1e6,
                        spectra_tpm=None):
        """starCAT-compatible reference spectra (``cnmf.py:1259-1290``):
        TPM spectra renormalized to ``target_sum`` per program, divided by
        per-gene TPM std, subset to HVGs, rows labeled ``GEP%d``.

        ``spectra_tpm``: the in-memory TPM-spectra DataFrame, passed by
        ``consensus`` so a same-process build skips re-parsing the txt
        artifact it just wrote (~0.6 s of a ~2.5 s warm consensus at
        north-star shape); standalone calls load it from disk. The txt
        round-trip quantizes values (to_csv default precision), so the
        in-memory path is MORE exact; golden artifact tests hold either
        way."""
        dt_repl = str(density_threshold).replace(".", "_")
        if spectra_tpm is None:
            spectra_tpm = pd.read_csv(
                self.paths["gene_spectra_tpm__txt"] % (k, dt_repl),
                index_col=0, sep="\t")
        else:
            spectra_tpm = spectra_tpm.copy()
        hvgs = open(self.paths["nmf_genes_list"]).read().split("\n")
        tpm_stats = load_df_from_npz(self.paths["tpm_stats"])
        tpm_stats.index = spectra_tpm.columns

        renorm = spectra_tpm.div(spectra_tpm.sum(axis=1), axis=0) * target_sum
        varnorm = renorm.div(tpm_stats["__std"])
        ref_spectra = varnorm[hvgs].copy()
        ref_spectra.index = "GEP" + ref_spectra.index.astype("str")

        save_df_to_npz(ref_spectra,
                       self.paths["starcat_spectra"] % (k, dt_repl))
        save_df_to_text(ref_spectra,
                        self.paths["starcat_spectra__txt"] % (k, dt_repl))

    @_timed("k_selection_plot")
    def k_selection_plot(self, close_fig=False):
        """Stability (silhouette) / error curve over the K sweep
        (``cnmf.py:1293-1332``; method credit Alexandrov et al. 2013)."""
        import concurrent.futures

        run_params = load_df_from_npz(self.paths["nmf_replicate_parameters"])
        # streaming K-selection (ISSUE 13): under a store-authoritative
        # prepare the error curve needs only HᵀX / HᵀH / ‖X‖², so ONE
        # budget-bounded slab pass over the store serves every K — the
        # full matrix is never assembled on host
        store = self._consensus_stream_store()
        norm_counts = (self._store_anndata(store) if store is not None
                       else self._read_norm_counts())
        ks_sorted = sorted(set(run_params.n_components))
        if not ks_sorted:
            raise ValueError(
                "k_selection_plot: the replicate ledger lists no components"
                " — run prepare() with a non-empty components list first")

        # every K's stats pass dispatches through ONE K_max/R_max-padded
        # program set (packed kmeans / silhouette / usage refit — padding
        # parity argued at their definitions), so a 9-K sweep uploads 3
        # executables instead of ~27; the ledger gives each K's merged-
        # spectra row count (over-estimates on dead-worker runs fall back
        # per-K inside consensus)
        R_by_k = {int(k): int((run_params.n_components == k).sum()) * int(k)
                  for k in ks_sorted}
        packed_dims = (max(R_by_k.values()), int(max(ks_sorted)))
        # ONE sweep-level sketch decision (from R_max) for every K's
        # stats pass — see consensus(_sketch_override=...)
        sk_sweep = resolve_consensus_sketch(int(packed_dims[0]),
                                            int(norm_counts.X.shape[1]))
        self._events.emit(
            "dispatch", decision="k_selection",
            context=dict(
                sk_sweep.as_context(),
                ks=[int(x) for x in ks_sorted],
                R_max=int(packed_dims[0]), K_max=int(packed_dims[1]),
                packed=True))

        if store is None:
            # the pool threads below must only ever HIT these caches:
            # neither _stage_dense nor the x_sq fingerprint pass is
            # safe/cheap under simultaneous misses (up to 4 concurrent
            # dataset-sized uploads / float64 passes), so both populate
            # serially here. Streaming runs skip both — their X work is
            # the one shared slab pass after the clustering stages.
            self._stage_dense("norm_counts", norm_counts.X)
            tok = self._content_token(norm_counts.X)
            if tok not in self._x_sq_cache:
                self._x_sq_cache[tok] = _x_squared_sum(norm_counts.X)

        if env_flag("CNMF_WARM_CONSENSUS", True) and store is None:
            # warm the packed program set concurrently up front: each
            # executable's first dispatch pays a ~2 s program-upload round
            # trip on a tunneled chip regardless of compile caching
            # (streaming runs skip it — the refit-warm dummies are
            # dataset-sized, exactly what the slab budget forbids)
            self._warm_kselection_packed(
                packed_dims[0], packed_dims[1], norm_counts.X.shape[0],
                norm_counts.X.shape[1])

        # the 9 Ks' stats passes are independent (shared state — the staged
        # norm_counts, the x_sq fingerprint, the packed executables — is
        # read-only by here), and each pass is a chain of small device
        # dispatches whose tunnel round-trips dominate its wall-clock;
        # running them in a thread pool overlaps the RTTs of one K with
        # the host pandas work of another (measured: 9-K cold 29.5 s ->
        # 14.7-19.9 s, warm 18.1 s -> 5.9-10 s)
        # streaming mode: each K's stats pass defers its prediction
        # error into this collector (clustering/silhouette are spectra-
        # only), then ONE slab pass over the store fills every cell
        error_collector: dict = {} if store is not None else None

        def stats_for(k):
            return self.consensus(
                int(k), skip_density_and_return_after_stats=True,
                show_clustering=False, close_clustergram_fig=True,
                norm_counts=norm_counts, _packed_dims=packed_dims,
                _sketch_override=sk_sweep, _stream_store=store,
                _stream_error_collector=error_collector).stats

        with concurrent.futures.ThreadPoolExecutor(
                min(4, len(ks_sorted))) as ex:
            stats = list(ex.map(stats_for, [int(k) for k in ks_sorted]))
        if error_collector:
            with self._timer.stage("k_selection.stream_errors"):
                errs = self._streamed_prediction_errors(store,
                                                        error_collector)
            for s in stats:
                s["prediction_error"] = errs[int(s["k"])]
        # a per-K fallback (ledger over-estimate) routes through
        # _warm_consensus_programs, whose shared dummy buffers are
        # dataset-sized device arrays — release them
        self._warm_dummies.clear()
        stats = pd.DataFrame(stats)
        stats.reset_index(drop=True, inplace=True)
        save_df_to_npz(stats, self.paths["k_selection_stats"])

        from .plots import k_selection_figure

        k_selection_figure(stats, self.paths["k_selection_plot"],
                           close_fig=close_fig)
        return stats

    def load_results(self, K, density_threshold, n_top_genes=100,
                     norm_usage=True):
        """Read final txt artifacts; returns
        ``(usage, spectra_scores, spectra_tpm, top_genes)``
        (``cnmf.py:1335-1384``)."""
        dt_repl = str(density_threshold).replace(".", "_")
        spectra_scores = pd.read_csv(
            self.paths["gene_spectra_score__txt"] % (K, dt_repl),
            sep="\t", index_col=0).T
        spectra_tpm = pd.read_csv(
            self.paths["gene_spectra_tpm__txt"] % (K, dt_repl),
            sep="\t", index_col=0).T
        usage = pd.read_csv(
            self.paths["consensus_usages__txt"] % (K, dt_repl),
            sep="\t", index_col=0)
        if norm_usage:
            usage = usage.div(usage.sum(axis=1), axis=0)
        try:
            usage.columns = [int(x) for x in usage.columns]
        except ValueError:
            print("Usage matrix columns include non integer values")

        top_genes = []
        for gep in spectra_scores.columns:
            top_genes.append(list(
                spectra_scores.sort_values(by=gep, ascending=False)
                .index[:n_top_genes]))
        top_genes = pd.DataFrame(top_genes,
                                 index=spectra_scores.columns).T
        return usage, spectra_scores, spectra_tpm, top_genes


def _x_squared_sum(X) -> float:
    """||X||_F^2 in float64 — separable from the prediction error so a
    K-selection sweep computes it once per matrix, not once per K."""
    if sp.issparse(X):
        return float((X.multiply(X)).sum())
    Xd = np.asarray(X, dtype=np.float64)
    return float((Xd * Xd).sum())


def _frobenius_prediction_error(X, H, W, x_sq: float | None = None) -> float:
    """||X - HW||_F^2 without materializing a dense cells x genes buffer for
    sparse X: the trace identity needs only H^T X (k x g via sparse matmul),
    H^T H, and ||X||^2 — the reference's ``todense()`` at cnmf.py:1100-1104
    is its single most memory-hungry line (SURVEY.md §3.4). Float64
    accumulation keeps the cancellation harmless."""
    H = np.asarray(H, dtype=np.float64)
    W = np.asarray(W, dtype=np.float64)
    if x_sq is None:
        x_sq = _x_squared_sum(X)
    if sp.issparse(X):
        HtX = np.asarray((X.T @ H).T)  # k x g
    else:
        HtX = H.T @ np.asarray(X, dtype=np.float64)
    cross = float(np.sum(HtX * W))
    HtH = H.T @ H
    hw_sq = float(np.sum((HtH @ W) * W))
    return max(x_sq - 2.0 * cross + hw_sq, 0.0)
