"""Resident reference spectra for the warm serving tier (ISSUE 12).

A projection request is a ``fit_h`` refit against *published* reference
spectra — the one matrix every request shares. This module loads that
matrix ONCE per daemon process and holds it device-resident together
with its loop-invariant products, so a request pays only its own usage
solve:

  * ``W`` (k x genes, f32) staged through the pipelined staging engine
    (:func:`~cnmf_torch_tpu.parallel.streaming.stream_to_device` — the
    same slab-wise path factorize stages through, so an atlas-wide
    reference never needs a second host copy);
  * ``WWT = W @ W.T`` for beta=2 and the per-component column sums for
    beta in {1, 0} — the hoisted loop-invariant MU products (arXiv
    1107.5194's observation applied to the serving tier: they are
    constant across every request and every inner iteration);
  * the solo-dispatch solver parameters (beta, chunk size, inner cap,
    tolerance, l1) read from the run's ``nmf_idvrun_params.yaml`` — the
    EXACT parameters :meth:`cNMF.refit_usage` would pass, which is what
    makes the batched serve path bit-identical to solo dispatch.

Reference resolution: a run directory that has been through
``consensus`` holds one consensus-spectra artifact per (k, density
threshold); ``find_references`` enumerates them and ``load_reference``
picks by (k, dt) or uniquely. Atlas-scale references may instead live in
a digest-validated :class:`~cnmf_torch_tpu.utils.shardstore.ShardStore`
directory (rows = components): pass its path as ``spectra_path`` and the
slabs stream through the validated reader.
"""

from __future__ import annotations

import os
import re

import numpy as np

__all__ = ["ReferenceError", "ResidentReference", "find_references",
           "load_reference"]


class ReferenceError(ValueError):
    """No (or ambiguous) reference spectra for the requested run/k/dt."""


def find_references(run_dir: str) -> list[dict]:
    """Enumerate consensus-spectra artifacts under ``run_dir`` as
    ``{"k", "dt", "path"}`` rows (sorted by k then dt)."""
    name = os.path.basename(os.path.normpath(run_dir))
    tmp = os.path.join(run_dir, "cnmf_tmp")
    if not os.path.isdir(tmp):
        return []
    pat = re.compile(
        re.escape(name) + r"\.spectra\.k_(\d+)\.dt_([0-9_]+)\.consensus"
        r"\.df\.npz$")
    out = []
    for fn in sorted(os.listdir(tmp)):
        m = pat.match(fn)
        if m:
            out.append({"k": int(m.group(1)),
                        "dt": m.group(2).replace("_", "."),
                        "path": os.path.join(tmp, fn)})
    return sorted(out, key=lambda r: (r["k"], r["dt"]))


def _load_run_params(run_dir: str) -> dict:
    """The run's solver-parameter YAML (the refit contract source)."""
    import yaml

    name = os.path.basename(os.path.normpath(run_dir))
    path = os.path.join(run_dir, "cnmf_tmp",
                        name + ".nmf_idvrun_params.yaml")
    if not os.path.exists(path):
        raise ReferenceError(
            f"no solver parameters at {path} — serve needs a prepared run "
            f"directory (output_dir/name with cnmf_tmp/)")
    with open(path) as f:
        return yaml.load(f, Loader=yaml.FullLoader)


class ResidentReference:
    """One published reference, loaded once and held device-resident.

    Host side: ``W`` (k x genes f32), ``genes`` (column labels, None for
    store-backed references without names), ``components`` (row labels),
    and the solo-dispatch solver params. Device side (after
    :meth:`stage`): ``Wd``, ``WWT`` (beta=2) or ``w_colsum`` (beta 1/0),
    and the device-resident tolerance scalar — everything the batched
    dispatch touches, so the hot path runs with zero implicit host
    transfers (pinned under ``jax.transfer_guard`` in
    ``tests/test_serving.py``).
    """

    def __init__(self, W: np.ndarray, *, beta: float, chunk_size: int,
                 chunk_max_iter: int, h_tol: float = 0.05,
                 l1_H: float = 0.0, genes=None, components=None,
                 k: int | None = None, density_threshold=None,
                 source: str = "memory"):
        W = np.ascontiguousarray(np.asarray(W, dtype=np.float32))
        if W.ndim != 2 or not W.size:
            raise ReferenceError(
                f"reference spectra must be a (k, genes) matrix, got "
                f"shape {W.shape}")
        if not np.isfinite(W).all():
            raise ReferenceError("reference spectra contain nonfinite "
                                 "values; refusing to serve them")
        self.W = W
        self.beta = float(beta)
        self.chunk_size = int(chunk_size)
        self.chunk_max_iter = int(chunk_max_iter)
        self.h_tol = float(h_tol)
        self.l1_H = float(l1_H)
        self.genes = list(genes) if genes is not None else None
        self.components = (list(components) if components is not None
                           else list(range(1, W.shape[0] + 1)))
        self.k = int(k if k is not None else W.shape[0])
        self.density_threshold = density_threshold
        self.source = source
        # device residents (stage())
        self.Wd = None
        self.WWT = None
        self.w_colsum = None
        self.h_tol_dev = None
        self.stage_stats = None

    @property
    def n_genes(self) -> int:
        return int(self.W.shape[1])

    def describe(self) -> dict:
        return {"source": self.source, "k": self.k,
                "n_genes": self.n_genes, "beta": self.beta,
                "density_threshold": self.density_threshold,
                "chunk_size": self.chunk_size,
                "chunk_max_iter": self.chunk_max_iter,
                "h_tol": self.h_tol, "l1_H": self.l1_H,
                "resident": self.Wd is not None}

    def stage(self, events=None):
        """Upload W through the pipelined staging engine and precompute
        the loop-invariant products. Idempotent; returns self."""
        if self.Wd is not None:
            return self
        import jax
        import jax.numpy as jnp

        from ..parallel.streaming import StreamStats, stream_to_device

        stats = StreamStats()
        self.Wd = jax.block_until_ready(
            stream_to_device(self.W, stats=stats, events=events))
        self.stage_stats = stats
        if self.beta == 2.0:
            # the beta=2 solo refit computes WWT once per call inside
            # _fit_h_chunked; here it is computed once per DAEMON — the
            # same jitted matmul, so the product is bit-equal to the one
            # the solo program derives
            self.WWT = jax.block_until_ready(
                jax.jit(lambda w: w @ w.T)(self.Wd))
        elif self.beta == 1.0:
            # the KL MU denominator is the W column sum, constant across
            # every request — computed once here and consumed by the
            # serve program (_update_H(w_colsum=)); same reduce op the
            # solo program runs, so results stay bit-equal. (IS has no
            # hoistable denominator product: its denom depends on H.)
            self.w_colsum = jax.block_until_ready(
                jax.jit(lambda w: jnp.sum(w, axis=1))(self.Wd))
        self.h_tol_dev = jax.device_put(np.float32(self.h_tol))
        return self


def load_reference(run_dir: str, k: int | None = None,
                   density_threshold=None,
                   spectra_path: str | None = None) -> ResidentReference:
    """Load a reference from a consensus-complete run directory (or an
    explicit spectra artifact / ShardStore directory) — host-side only;
    call :meth:`ResidentReference.stage` to make it device-resident."""
    params = _load_run_params(run_dir)
    from ..ops.nmf import beta_loss_to_float

    common = dict(
        beta=beta_loss_to_float(params["beta_loss"]),
        chunk_size=int(params["online_chunk_size"]),
        chunk_max_iter=int(params["online_chunk_max_iter"]),
        l1_H=float(params["l1_ratio_H"]))

    if spectra_path is not None:
        if os.path.isdir(spectra_path):
            # atlas-scale reference in a digest-validated shard store
            # (rows = components): every slab read re-verifies its
            # content digest, torn reads heal or fail loudly
            from ..utils.shardstore import open_shard_store

            store = open_shard_store(spectra_path)
            W = store.to_matrix()
            if hasattr(W, "toarray"):
                W = W.toarray()
            genes = None
            try:
                genes = store.var_names()
            except Exception:
                pass
            return ResidentReference(
                np.asarray(W), genes=genes, source=spectra_path, **common)
        from ..utils.io import load_df_from_npz

        df = load_df_from_npz(spectra_path)
        return ResidentReference(
            df.values, genes=df.columns, components=df.index,
            source=spectra_path, **common)

    refs = find_references(run_dir)
    if k is not None:
        refs = [r for r in refs if r["k"] == int(k)]
    if density_threshold is not None:
        dt = str(density_threshold)
        refs = [r for r in refs if r["dt"] == dt]
    if not refs:
        raise ReferenceError(
            f"no consensus spectra found under {run_dir}"
            + (f" for k={k}" if k is not None else "")
            + (f" dt={density_threshold}"
               if density_threshold is not None else "")
            + " — run `cnmf-tpu consensus` first")
    if len(refs) > 1:
        choices = ", ".join(f"k={r['k']} dt={r['dt']}" for r in refs)
        raise ReferenceError(
            f"multiple consensus spectra under {run_dir} ({choices}); "
            f"pick one with -k / --local-density-threshold")
    ref = refs[0]
    from ..utils.io import load_df_from_npz

    df = load_df_from_npz(ref["path"])
    return ResidentReference(
        df.values, genes=df.columns, components=df.index,
        k=ref["k"], density_threshold=ref["dt"], source=ref["path"],
        **common)
