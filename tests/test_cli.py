"""CLI coverage — the tier the reference lacks entirely (SURVEY.md §4:
"the CLI [has] no automated tests"). Drives the same five-subcommand flow as
``Extras/run_parallel.py``, including the worker-sharded factorize the
reference fork's CLI broke (its --worker-index flag is commented out,
cnmf.py:1430, while its docs still use it)."""

import os

import numpy as np
import pandas as pd
import pytest

from cnmf_torch_tpu.cli import main
from cnmf_torch_tpu.utils import build_paths, load_df_from_npz, save_df_to_npz


@pytest.fixture(scope="module")
def counts_file(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("cli_data")
    rng = np.random.default_rng(3)
    usage = rng.dirichlet(np.ones(3) * 0.3, size=80)
    spectra = rng.gamma(0.3, 1.0, size=(3, 200)) * 50.0 / 200
    counts = rng.poisson(usage @ spectra * 250.0).astype(np.float64)
    counts[counts.sum(axis=1) == 0, 0] = 1.0
    df = pd.DataFrame(counts, index=[f"c{i}" for i in range(80)],
                      columns=[f"g{j}" for j in range(200)])
    fn = str(tmp / "counts.df.npz")
    save_df_to_npz(df, fn)
    return fn


def test_cli_full_flow(tmp_path, counts_file):
    out = str(tmp_path)
    base = ["--output-dir", out, "--name", "cli_run"]
    main(["prepare", *base, "-c", counts_file, "-k", "3", "4",
          "--n-iter", "4", "--seed", "10", "--numgenes", "150",
          "--batch_size", "64", "--max-nmf-iter", "100"])
    paths = build_paths(out, "cli_run", create=False)
    assert os.path.exists(paths["nmf_replicate_parameters"])

    # worker-sharded factorize: two workers, disjoint shards (the repaired
    # --worker-index path)
    main(["factorize", *base, "--worker-index", "0", "--total-workers", "2"])
    main(["factorize", *base, "--worker-index", "1", "--total-workers", "2"])
    for k in (3, 4):
        for it in range(4):
            assert os.path.exists(paths["iter_spectra"] % (k, it))

    main(["combine", *base])
    assert load_df_from_npz(paths["merged_spectra"] % 3).shape[0] == 12

    main(["consensus", *base, "-k", "3",
          "--local-density-threshold", "2.0", "--show-clustering"])
    assert os.path.exists(paths["consensus_usages"] % (3, "2_0"))
    assert os.path.exists(paths["starcat_spectra"] % (3, "2_0"))
    assert os.path.exists(paths["clustering_plot"] % (3, "2_0"))

    main(["k_selection_plot", *base])
    assert os.path.exists(paths["k_selection_stats"])
    assert os.path.exists(paths["k_selection_plot"])


def test_cli_skip_completed(tmp_path, counts_file):
    out = str(tmp_path)
    base = ["--output-dir", out, "--name", "resume"]
    main(["prepare", *base, "-c", counts_file, "-k", "3", "--n-iter", "3",
          "--seed", "1", "--numgenes", "100", "--batch_size", "64",
          "--max-nmf-iter", "50"])
    paths = build_paths(out, "resume", create=False)
    # one worker of two -> iters 0 and 2 done
    main(["factorize", *base, "--worker-index", "0", "--total-workers", "2"])
    assert not os.path.exists(paths["iter_spectra"] % (3, 1))
    # re-prepare probes the disk and marks completed; skip-completed reruns
    # only the gap
    main(["prepare", *base, "-c", counts_file, "-k", "3", "--n-iter", "3",
          "--seed", "1", "--numgenes", "100", "--batch_size", "64",
          "--max-nmf-iter", "50"])
    ledger = load_df_from_npz(paths["nmf_replicate_parameters"])
    assert list(ledger.completed) == [True, False, True]
    main(["factorize", *base, "--skip-completed-runs", "--total-workers", "1"])
    assert os.path.exists(paths["iter_spectra"] % (3, 1))


def test_cli_rejects_bad_command(capsys):
    with pytest.raises(SystemExit):
        main(["frobnicate"])


@pytest.mark.parametrize("command", ["prepare", "run_parallel"])
def test_cli_requires_counts_and_components(command, capsys):
    """Omitting -c/-k must die as a usage error, not a traceback from deep
    inside prepare (advisor finding, round 3)."""
    with pytest.raises(SystemExit) as exc:
        main([command, "--output-dir", "/tmp/nonexistent-cnmf-test"])
    assert exc.value.code == 2
    err = capsys.readouterr().err
    assert "--counts" in err and "--components" in err
